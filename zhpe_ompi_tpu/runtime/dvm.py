"""zprted — the persistent runtime daemon (PRRTE/DVM analog).

In the reference, ``mpirun`` is a symlink to the external ``prte`` binary
(``ompi/tools/mpirun/Makefile.am:11-15``): a *resident* runtime hosts the
PMIx server, launches jobs into itself, watches its children, and owns
fault notification — none of which lives in the MPI tree.  This module is
that daemon IN tree, the elastic-launcher / coordinator-service layer the
fault-tolerance planes of PRs 1–7 built toward:

- **resident PMIx store** (:mod:`.pmix`): one server outlives every job;
  ``zmpirun --dvm`` launches a job into the running VM and the ranks
  modex through the store — no per-job rendezvous coordinator, no name
  server, no launcher interpreter start-up (the launch-latency win the
  OSU ``--launch`` ladder gates).
- **authoritative fault events**: the daemon ``waitpid``-watches every
  child (one *blocking* ``wait()`` thread per proc — no polling in the
  hot path) and, the moment a rank of an ft job dies, floods an
  ``FT_DVM_CID`` control frame to every survivor.  That is OS truth —
  the corpse's exit status — feeding the same
  :class:`~zhpe_ompi_tpu.ft.ulfm.FailureState` as the ring heartbeats,
  marking the rank failed (``cause="daemon"``) before a single detector
  timeout expires.
- **relaunch RPC**: :func:`~zhpe_ompi_tpu.ft.recovery.daemon_respawn`
  asks the daemon to exec a fresh OS process into a dead rank's slot;
  the replacement FT_JOINs the name-served job (``TcpProc(rejoin=True)``
  fetches the book from the store), closing the recovery pipeline over
  real processes end to end.  One respawn RPC may carry N victims — the
  namespace generation is bumped ONCE, so the whole batch joins the
  same recovery window.

Wire protocol (control port; length-framed DSS, request/response with
streaming for ``launch``): requests are ``["launch", spec]``,
``["respawn", job, ranks]``, ``["pids", job]``, ``["stat"]``,
``["metrics", job[, rank]]``, ``["ping"]``, ``["stop"]``.  A launch
streams ``["job", id]``, then ``["io", rank, label, line]`` /
``["note", text]`` frames, and finally ``["exit", rc]``.

The daemon is also the metrics plane's aggregation point: ranks
launched with ``metrics=True`` (``ZMPI_METRICS=1``) publish
generation-tagged ``metrics:<job>:<rank>`` snapshots into the resident
store, the ``metrics`` RPC serves per-rank / per-job / job-aggregated
views with staleness stamps, and — off by default, ``--metrics-port``
to enable — an HTTP ``GET /metrics`` listener emits the whole store's
counter plane as Prometheus text exposition
(``zmpi_spc_<name>{job="...",rank="..."} value``), so the han/sm/wire/
FT counters the benches gate on are scrapeable from a live fleet.

Job semantics mirror ``zmpirun``: non-ft jobs keep MPI_Abort teardown
(first nonzero exit kills the rest); ft jobs keep running — death is an
event for the survivors' recovery pipeline, not a job teardown.

Hygiene is observable: every in-process daemon registers weakly
(:func:`live_dvms` must be empty once tests stop theirs), daemon
*processes* are found by cmdline scan (:func:`orphaned_daemon_processes`),
and a stopping daemon destroys its jobs' namespaces and sweeps their
``/dev/shm`` artifacts exactly as the ``zmpirun`` session sweep does.

CLI (the ``zprted`` entrypoint)::

    python -m zhpe_ompi_tpu.runtime.dvm [--host H] [--port P] [--pmix-port Q]

prints ``zprted ready dvm=H:P pmix=H:Q`` once both listeners are up, and
runs until SIGTERM/SIGINT or a ``stop`` RPC.
"""

from __future__ import annotations

import argparse
import itertools
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import weakref
from typing import Any

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from . import dvmtree
from . import flightrec
from . import pmix as pmix_mod
from . import spc
from . import ztrace

_stream = mca_output.open_stream("dvm")

mca_var.register(
    "dvm_job_timeout", 600.0,
    "Default wall-clock deadline (seconds) for a daemon-hosted job "
    "that did not pass its own timeout: a wedged rank set may not park "
    "a zprted launch handler forever",
    type=float,
)

mca_var.register(
    "dvm_admission_policy", "fifo",
    "Launch-admission ordering on a daemon: 'fifo' admits in arrival "
    "order, 'priority' by descending launch priority= (ties by "
    "arrival) — re-evaluated each time a slot frees, so a late "
    "high-priority arrival preempts the QUEUE order (never a running "
    "job)",
)

mca_var.register(
    "dvm_max_concurrent_jobs", 0,
    "Concurrently RUNNING jobs a daemon admits; excess launches BLOCK "
    "as tickets in the admission queue (the client streams [queued, "
    "position] frames while it waits) until a running job completes; "
    "<= 0 is unbounded (the single-tenant default)",
    type=int,
)

_TERM_GRACE = 2.0  # seconds between SIGTERM and SIGKILL on teardown

# IOF-drain deadline at job exit: once every child is dead its pipes
# are at EOF, so a drain finishes after finitely many reads — but a
# drain thread STARVED by scheduler load past a short per-thread join
# loses the rank's final lines to a client that stopped reading at the
# exit frame (the TestDvmMultiVictimRecovery finalize-skew flake: the
# last SURVIVOR-OK line raced the exit frame under full-suite load).
# One generous SHARED deadline covers starvation; only a leaked
# grandchild holding a dead child's pipe open can exhaust it, and that
# pathology is reported loudly instead of surfacing as truncation.
_IOF_DRAIN_GRACE = 30.0

_live_dvms: weakref.WeakSet = weakref.WeakSet()


def live_dvms() -> list[str]:
    """In-process daemons still listening — must be [] once tests stop
    theirs (a leaked daemon holds two ports and a PMIx store)."""
    return [
        f"dvm:{d.address[0]}:{d.address[1]}"
        for d in list(_live_dvms)
        if not d.stopped
    ]


def orphaned_daemon_processes() -> list[str]:
    """zprted processes still alive on this host (cmdline scan) — the
    session gate's view: no daemon subprocess may outlive the test that
    spawned it."""
    out = []
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return out  # no /proc: nothing to scan
    for pid in pids:
        if int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                args = [a.decode("utf-8", "replace")
                        for a in f.read().split(b"\0") if a]
        except OSError:
            continue  # raced an exit
        # match ACTUAL daemon invocations only ("python -m
        # zhpe_ompi_tpu.runtime.dvm ..." or a zprted binary) — a
        # substring match would flag any shell/pytest line that merely
        # MENTIONS zprted (e.g. running a test by its name)
        if any(a == "zhpe_ompi_tpu.runtime.dvm" for a in args) or (
                args and os.path.basename(args[0]) == "zprted"):
            out.append(f"pid {pid}: {' '.join(args)}")
    return out


_live_metrics_http: weakref.WeakSet = weakref.WeakSet()


def live_metrics_listeners() -> list[str]:
    """Metrics HTTP listeners still bound — must be [] once every
    daemon's stop() ran (the scrape endpoint dies with its daemon)."""
    return [
        f"metrics-http:{h.address[0]}:{h.address[1]}"
        for h in list(_live_metrics_http)
        if not h.closed
    ]


class MetricsHttpListener:
    """Minimal HTTP/1.0 server for ``GET /metrics``: one accept loop,
    one short-lived thread per request, Prometheus text exposition
    rendered by the owning daemon.  Deliberately tiny — no keep-alive,
    no routing beyond /metrics, request read bounded — because its
    whole contract is "a scraper can poll this port"."""

    def __init__(self, dvm: "Dvm", host: str, port: int):
        self._dvm = dvm
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._srv.bind((host, port))
        except OSError:
            self._srv.close()
            raise
        self._srv.listen(8)
        self.address: tuple[str, int] = self._srv.getsockname()
        self.closed = False
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._acceptor = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"dvm-metrics-http-{self.address[1]}",
        )
        self._acceptor.start()
        _live_metrics_http.add(self)

    def _accept_loop(self) -> None:
        while not self.closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._lock:
                self._threads = [t for t in self._threads if t.is_alive()]
                t = threading.Thread(
                    target=self._serve, args=(conn,), daemon=True,
                    name=f"dvm-metrics-req-{self.address[1]}",
                )
                self._threads.append(t)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            data = b""
            while b"\r\n\r\n" not in data and len(data) < 8192:
                chunk = conn.recv(1024)
                if not chunk:
                    return
                data += chunk
            line = data.split(b"\r\n", 1)[0].decode("ascii", "replace")
            parts = line.split()
            path = parts[1] if len(parts) >= 2 else ""
            if parts and parts[0] == "GET" \
                    and path.split("?", 1)[0] == "/metrics":
                body = self._dvm.prometheus().encode("utf-8")
                head = (
                    "HTTP/1.0 200 OK\r\n"
                    "Content-Type: text/plain; version=0.0.4; "
                    "charset=utf-8\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n"
                )
            else:
                body = b"not found\n"
                head = ("HTTP/1.0 404 Not Found\r\n"
                        "Content-Type: text/plain\r\n"
                        f"Content-Length: {len(body)}\r\n\r\n")
            conn.sendall(head.encode("ascii") + body)
        except OSError:
            return  # scraper went away mid-request: its own problem
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        deadline = time.monotonic() + 5.0
        self._acceptor.join(max(0.0, deadline - time.monotonic()))
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))


def _sweep_shm(session: str) -> None:
    """Session-directory cleanup for one session tag (the zmpirun sweep,
    shared prefix scheme): killed ranks never unlink their rings."""
    try:
        for f in os.listdir("/dev/shm"):
            if f.startswith((f"zompi_ring_{session}_",
                             f"zompi_shm_{session}_",
                             f"zompi_pyring_{session}_")):
                try:
                    os.unlink(os.path.join("/dev/shm", f))
                except OSError:
                    pass
    except OSError:
        pass


def _tree_query(addr: tuple[str, int]) -> dict:
    """One ``treeinfo`` RPC against a daemon (the attach-time
    discovery: parent store address + depth)."""
    cli = DvmClient(addr, timeout=30.0)
    try:
        return cli.treeinfo()
    finally:
        cli.close()


_live_admission: weakref.WeakSet = weakref.WeakSet()


def queued_admission_tickets() -> list[str]:
    """Tickets still parked in any daemon's admission queue — must be
    [] at session end (the conftest gate): a leaked ticket means a
    launch handler died without cancel/release and the queue head is
    wedged forever."""
    out: list[str] = []
    for q in list(_live_admission):
        out += q.queued()
    return out


class _AdmissionTicket:
    """One launch's place in the admission queue: enqueue order,
    priority, and admission state."""

    def __init__(self, seq: int, priority: int):
        self.seq = seq
        self.priority = int(priority)
        self.t0 = time.monotonic()
        self.admitted = False
        self.was_queued = False


class _AdmissionQueue:
    """Explicit launch admission — the bare serializing lock's convoy
    made a POLICY.  An ordered ticket queue (fifo by arrival, or
    priority-then-arrival, per ``dvm_admission_policy``) bounded by
    ``dvm_max_concurrent_jobs``: excess launches BLOCK as tickets here
    (their clients stream ``[queued, position]`` frames) instead of
    convoying blindly on a mutex.  :meth:`setup` is the short job-setup
    critical section (id / namespace / placement / spawn loop — one
    job at a time, exactly the old lock's scope); an ADMITTED ticket
    additionally holds a concurrency slot until :meth:`release` at job
    end.  The respawn/resize RPCs take ``setup()`` directly — they
    ride their job's admission (that job is already running) so they
    can never queue behind a blocked launch, and a queued launch holds
    NO lock at all, so it cannot interleave a resizing job's
    membership."""

    def __init__(self):
        self._cv = threading.Condition()
        self._setup = threading.Lock()
        self._waiting: list[_AdmissionTicket] = []
        self._running = 0
        self._seq = itertools.count()
        self._closed = False
        _live_admission.add(self)

    def setup(self) -> threading.Lock:
        """The job-setup serialization lock (a context manager)."""
        return self._setup

    def enqueue(self, priority: int = 0) -> _AdmissionTicket:
        with self._cv:
            t = _AdmissionTicket(next(self._seq), priority)
            self._waiting.append(t)
            return t

    def _order(self) -> list[_AdmissionTicket]:
        # policy read PER evaluation: flipping the MCA var reorders the
        # live queue, it never needs a daemon restart
        if str(mca_var.get("dvm_admission_policy", "fifo")) \
                == "priority":
            return sorted(self._waiting,
                          key=lambda t: (-t.priority, t.seq))
        return sorted(self._waiting, key=lambda t: t.seq)

    def _admissible(self, ticket: _AdmissionTicket) -> bool:
        cap = int(mca_var.get("dvm_max_concurrent_jobs", 0))
        order = self._order()
        return bool(order) and order[0] is ticket \
            and (cap <= 0 or self._running < cap)

    def _position(self, ticket: _AdmissionTicket) -> int:
        for i, t in enumerate(self._order()):
            if t is ticket:
                return i + 1
        return 0

    def admit(self, ticket: _AdmissionTicket, alive=None,
              on_position=None) -> float | None:
        """Block until ``ticket`` is admitted.  Returns the seconds it
        waited, or None when ``alive()`` reported the client dead (the
        ticket is cancelled — a dead client's queued job is reaped,
        never left to wedge the queue head).  ``on_position(pos)``
        fires outside the queue lock whenever the queued position
        changes.  Raises InternalError when the queue closes under a
        waiter (daemon stop)."""
        notified = None
        while True:
            with self._cv:
                if self._closed:
                    self._discard(ticket)
                    raise errors.InternalError(
                        "zprted: daemon stopping — launch not admitted")
                if self._admissible(ticket):
                    self._waiting.remove(ticket)
                    ticket.admitted = True
                    self._running += 1
                    self._cv.notify_all()
                    return time.monotonic() - ticket.t0
                ticket.was_queued = True
                pos = self._position(ticket)
            # callbacks OUTSIDE the lock: a blocking client socket must
            # never wedge every other launch's admission
            if alive is not None and not alive():
                self.cancel(ticket)
                return None
            if on_position is not None and pos != notified:
                notified = pos
                on_position(pos)
            with self._cv:
                if not self._closed and not self._admissible(ticket) \
                        and self._position(ticket) == notified:
                    self._cv.wait(0.25)

    def cancel(self, ticket: _AdmissionTicket) -> None:
        with self._cv:
            self._discard(ticket)
            self._cv.notify_all()

    def _discard(self, ticket: _AdmissionTicket) -> None:
        if ticket in self._waiting:
            self._waiting.remove(ticket)

    def release(self, ticket: _AdmissionTicket) -> None:
        """Job over (or launch failed): free the concurrency slot and
        wake the queue.  Idempotent, and reaps a never-admitted ticket
        too — the one release in the launch handler's ``finally``
        covers every exit path."""
        with self._cv:
            if ticket.admitted:
                ticket.admitted = False
                self._running -= 1
            else:
                self._discard(ticket)
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def stat_view(self) -> dict:
        with self._cv:
            return {
                "policy": str(mca_var.get("dvm_admission_policy",
                                          "fifo")),
                "cap": int(mca_var.get("dvm_max_concurrent_jobs", 0)),
                "running": self._running,
                "waiting": len(self._waiting),
            }

    def queued(self) -> list[str]:
        with self._cv:
            return [f"admission-ticket:seq={t.seq}:prio={t.priority}"
                    for t in self._waiting]


class _Job:
    """One launched job: its procs (latest incarnation per rank), exit
    bookkeeping, and the IOF client connection.  On a TREE the root
    holds the authoritative copy — ``procs`` are its LOCAL ranks only,
    remote ranks live in ``remote_alive``/``remote_pids`` fed by
    ``exited``/``spawned`` frames riding up the links, and
    ``placement`` maps every rank to the daemon hosting it.  A child
    daemon holds a thin mirror (``conn=None``): local procs plus the
    spawn metadata its ``_rank_env`` needs."""

    def __init__(self, job_id: str, size: int, cmds: list[list[str]],
                 ft: bool, mca: list, session: str, conn, conn_lock,
                 metrics: bool = False, trace: bool = False):
        self.id = job_id
        self.size = size
        self.cmds = cmds
        self.ft = ft
        self.mca = mca
        self.metrics = metrics
        self.trace = trace
        self.session = session
        self.conn = conn              # IOF/exit stream target
        self.conn_lock = conn_lock
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.procs: dict[int, subprocess.Popen] = {}
        self.rcs: dict[int, int] = {}
        self.superseded: dict[int, list[subprocess.Popen]] = {}
        self.live = 0
        self.fail_rc: int | None = None
        self.stopping = False
        self.io_broken = False
        self.done = threading.Event()
        self.drains: list[threading.Thread] = []
        self.watchers: list[threading.Thread] = []
        # tree bookkeeping (root side)
        self.placement: dict[int, str] = {}
        # tenancy: this job got (and keeps) an exclusive daemon subtree
        self.exclusive = False
        self.remote_alive: set[int] = set()
        self.remote_pids: dict[int, int] = {}
        # elastic bookkeeping: the CURRENT live membership target
        # (size is the launch-time max), and the resize event sequence
        self.elastic = False
        self.target: set[int] = set(range(size))
        self.resize_seq = 0

    def alive_ranks(self) -> list[int]:
        """LOCAL ranks with a live OS process on THIS daemon."""
        with self.lock:
            return sorted(r for r, p in self.procs.items()
                          if p.poll() is None)

    def live_count(self) -> int:
        with self.lock:
            return self.live

    def stat_view(self) -> dict:
        """Point-in-time job summary — under ``lock``, so a stat RPC
        never iterates ``target`` while a resize mutates it."""
        with self.lock:
            return {"size": self.size, "ft": self.ft,
                    "live": self.live, "elastic": self.elastic,
                    "target": sorted(self.target),
                    "placement": [[int(r), d] for r, d in
                                  sorted(self.placement.items())],
                    "done": self.done.is_set()}

    def retired(self, rank: int) -> bool:
        """A slot the daemon itself retired (elastic shrink): its exit
        — even a SIGTERM from the escalation ladder — is a requested
        departure, not a job failure.  Call under ``lock``."""
        return self.elastic and rank not in self.target


class Dvm(pmix_mod.FramedRpcServer):
    """The resident daemon: PMIx store + control RPC + child watching.
    Constructible in-process (tests, benchmarks) or via the ``zprted``
    CLI as its own OS process.  The control port rides the shared
    framed-RPC scaffold (:class:`~zhpe_ompi_tpu.runtime.pmix.
    FramedRpcServer`): fast control verbs dispatch inline on the
    channel engine; the connection-owning shapes (``launch`` streams
    ``[job]``/``[io]``/``[note]``/``[exit]`` frames, ``attach`` serves
    a child daemon's tree link for its life, ``lifeline`` parks until
    daemon death) plus the slow membership RPCs (``respawn``/
    ``resize`` hold spawn-confirmation windows) detach to dedicated
    threads — bounded by tree fan-out and op kind, never by universe
    size."""

    _STREAMED_OPS = frozenset(
        {"launch", "attach", "lifeline", "respawn", "resize"})

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 pmix_port: int = 0, session_tag: str | None = None,
                 metrics_port: int | None = None,
                 parent: "tuple[str, int] | str | None" = None):
        self.host = host
        self._parent_addr = pmix_mod.parse_addr(parent) \
            if parent is not None else None
        self._parent_link: dvmtree.TreeLink | None = None
        self._children: dict[str, dvmtree.ChildLink] = {}
        self._tree_lock = threading.Lock()
        self.tree_depth = 0
        if self._parent_addr is None:
            # ROOT (or single-daemon) mode: the authoritative store,
            # with its generation/destroy mutations broadcast down the
            # tree as cache invalidations whichever surface they
            # arrived through (wire verb, respawn RPC, resize)
            self.store = pmix_mod.PmixStore()
            self.store.on_generation = self._on_store_generation
            self.store.on_destroy = self._on_store_destroy
        else:
            # CHILD mode: learn the parent's store address, then serve
            # OUR ranks from the routed (leaf-cached) verb surface — a
            # rank only ever talks to ITS host's daemon
            meta = _tree_query(self._parent_addr)
            self.tree_depth = int(meta.get("depth", 0)) + 1
            self.store = dvmtree.RoutedStore(tuple(meta["pmix"]))
        self.pmix = pmix_mod.PmixServer(host, pmix_port, store=self.store)
        self.metrics_http: MetricsHttpListener | None = None
        try:
            super().__init__(host, port, "dvm", backlog=16)
        except OSError:
            self.pmix.close()
            raise
        if metrics_port is not None:
            # scrape endpoint OFF by default: binding a port is an
            # explicit operator decision (--metrics-port)
            try:
                self.metrics_http = MetricsHttpListener(
                    self, host, int(metrics_port))
            except OSError:
                self.pmix.close()
                super().close()
                raise
        self.session = session_tag or f"d{self.address[1]}"
        self.id = f"{host}:{self.address[1]}"
        self._stop_evt = threading.Event()
        self._jobs: dict[str, _Job] = {}
        self._job_ids = itertools.count(1)
        self._lock = threading.Lock()
        # launch-RPC admission is an explicit QUEUE: job setup — id
        # allocation, namespace creation, placement, and the spawn
        # loop — still happens one job at a time (setup()), but
        # admission ORDER is policy (dvm_admission_policy) and
        # admission COUNT is bounded (dvm_max_concurrent_jobs), with
        # excess launches parked as tickets streaming [queued, pos]
        # frames (the wait for a job's exit never holds anything;
        # admitted jobs still RUN concurrently)
        self._admission = _AdmissionQueue()
        # ordered daemon membership for placement: this daemon first,
        # children (and their subtrees) in attach order (root only)
        self._placement_ids: list[str] = [self.id]
        self._stopping_tree = False
        if self._parent_addr is not None:
            info = {"id": self.id, "control": list(self.address),
                    "pmix": list(self.pmix.address)}
            try:
                self._parent_link = dvmtree.TreeLink(
                    self._parent_addr, info,
                    on_down=self._handle_down,
                    on_lost=self._parent_lost)
                self._parent_link.start()
            except BaseException:
                self.pmix.close()
                super().close()
                raise
        _live_dvms.add(self)
        mca_output.verbose(
            1, _stream, "zprted up: dvm=%s:%d pmix=%s:%d session=%s "
            "depth=%d", host, self.address[1], host,
            self.pmix.address[1], self.session, self.tree_depth,
        )

    # -- wire ------------------------------------------------------------

    @property
    def stopped(self) -> bool:
        return self.closed

    def _wants_stream(self, op) -> bool:
        # a CHILD daemon relays job-level RPCs to the root over a
        # blocking upstream call — that wait belongs on a detached
        # thread, never on the engine every other client rides
        if self._parent_link is not None and op in (
                "stat", "pids", "metrics"):
            return True
        return super()._wants_stream(op)

    def _handle_request(self, req: list, conn, conn_lock) -> Any:
        if req[0] == "launch":
            self._handle_launch(req[1], conn, conn_lock)
            return self.STREAMED
        if req[0] == "attach":
            return self._handle_attach(req[1], conn, conn_lock)
        if req[0] == "lifeline":
            # a daemon-hosted rank parks one connection here for its
            # whole life: daemon death closes it, and the rank's
            # lifeline thread exits the process — a dead daemon's
            # subtree takes its ranks with it (the PRRTE contract)
            from ..pt2pt.tcp import _recv_frame

            try:
                while not self.closed:
                    if _recv_frame(conn) is None:
                        break
            except OSError:
                pass
            return self.STREAMED
        return self._dispatch(req)

    def _after_reply(self, req: list) -> bool:
        if req[0] == "stop":
            self.stop()
            return False
        return True

    def _dispatch(self, req: list) -> Any:
        op = req[0]
        if op == "ping":
            return "pong"
        if op == "treeinfo":
            with self._tree_lock:
                daemons = list(self._placement_ids)
            return {
                "id": self.id,
                "pmix": list(self.pmix.address),
                "depth": self.tree_depth,
                "root": self._parent_link is None,
                "daemons": daemons,
            }
        if op == "stop":
            return True
        if self._parent_link is not None and op in (
                "stat", "pids", "metrics", "respawn", "resize"):
            # a CHILD daemon relays job-level RPCs toward the root (a
            # rank only ever talks to ITS host's daemon — its
            # ZMPI_DVM respawn/resize calls land here and climb)
            return self._relay_up(req)
        if op == "stat":
            with self._lock:
                jobs = {j.id: j.stat_view()
                        for j in self._jobs.values()}
            with self._tree_lock:
                daemons = list(self._placement_ids)
            counters = spc.snapshot()
            return {
                "jobs": jobs,
                "pmix": self.store.stat(),
                "daemons": daemons,
                "admission": self._admission.stat_view(),
                "dvm_jobs_launched": counters.get("dvm_jobs_launched", 0),
                "dvm_fault_events": counters.get("dvm_fault_events", 0),
                "dvm_respawns": counters.get("dvm_respawns", 0),
                "dvm_resizes": counters.get("dvm_resizes", 0),
                "dvm_tree_forwards": counters.get("dvm_tree_forwards", 0),
                "dvm_store_cache_hits":
                    counters.get("dvm_store_cache_hits", 0),
                # scale-out-fabric gates: a REAL-process tree's scaling
                # tests can only see the root daemon's counters through
                # this RPC (each zprted has its own spc registry)
                "pmix_gets": counters.get("pmix_gets", 0),
                "dvm_tree_routed_launches":
                    counters.get("dvm_tree_routed_launches", 0),
                "store_leaf_cache_hits":
                    counters.get("store_leaf_cache_hits", 0),
                "store_leaf_cache_misses":
                    counters.get("store_leaf_cache_misses", 0),
            }
        if op == "pids":
            job = self._job(req[1])
            with job.lock:
                pids = dict(job.remote_pids)
                pids.update({int(r): p.pid
                             for r, p in job.procs.items()})
            return pids
        if op == "metrics":
            return self._metrics_view(
                str(req[1]), None if len(req) < 3 or req[2] is None
                else int(req[2]))
        if op == "respawn":
            return self._handle_respawn(req[1], [int(r) for r in req[2]])
        if op == "resize":
            return self._handle_resize(str(req[1]), int(req[2]))
        raise errors.ArgError(f"zprted: unknown request {op!r}")

    def _relay_up(self, req: list) -> Any:
        # the wait must outlast the ROOT's own worst case — a shrink
        # holds its full retire grace, a grow/respawn its remote spawn
        # confirmation window — or the relay would time out an RPC the
        # root goes on to apply (and a retry would double-apply)
        cli = DvmClient(self._parent_addr, timeout=60.0)
        try:
            return cli._call(req, wait=120.0)
        finally:
            cli.close()

    def _job(self, job_id: str) -> _Job:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise errors.ArgError(f"zprted: unknown job {job_id!r}")
        return job

    # -- tree links (parent/child daemon plumbing) ------------------------

    def _handle_attach(self, info: dict, conn, conn_lock) -> Any:
        """A child daemon's persistent tree link: register it, reply
        with our store coordinates, then SERVE the link on this
        handler thread — upward frames dispatch until EOF, and EOF
        without a prior orderly detach IS the child's death."""
        from ..pt2pt.tcp import _recv_frame, _send_frame
        from ..utils import dss

        child = dvmtree.ChildLink(info, conn, conn_lock)
        # registration and the handshake reply are ONE atomic step
        # under the link's send lock: registered-before-reply means a
        # launch racing the attach either misses the child entirely or
        # sees it fully placeable, and holding conn_lock across both
        # means no broadcast can slip a down-frame onto the wire AHEAD
        # of the ["ok", ...] the child's constructor is parked on
        reply = ["ok", {"pmix": list(self.pmix.address),
                        "depth": self.tree_depth, "id": self.id}]
        with conn_lock:
            with self._tree_lock:
                self._children[child.id] = child
            self._daemon_up([child.id], via_child=None)
            _send_frame(conn, dss.pack(reply))
        mca_output.verbose(
            1, _stream, "tree: child daemon %s attached (depth %d)",
            child.id, self.tree_depth + 1,
        )
        try:
            while not self.closed:
                frame = _recv_frame(conn)
                if frame is None:
                    break
                [msg] = dss.unpack(frame)
                if msg[0] != "up":
                    continue  # foreign frame shape on a tree link
                self._handle_up(child, str(msg[1]), msg[2])
        except OSError:
            pass
        finally:
            with self._tree_lock:
                self._children.pop(child.id, None)
            if not child.detached and not self.closed:
                self._child_lost(child)
        return self.STREAMED

    def _daemon_up(self, ids: list[str], via_child) -> None:
        """New daemon(s) joined the subtree: remember which link leads
        to them, then report up — the ROOT appends them to the
        placement order."""
        ids = [str(i) for i in ids]
        if via_child is not None:
            via_child.daemons.update(ids)
        if self._parent_link is not None:
            try:
                self._parent_link.send_up("daemon-up", ids)
            except OSError:
                pass  # parent gone: _parent_lost owns the teardown
            return
        with self._tree_lock:
            for i in ids:
                if i not in self._placement_ids:
                    self._placement_ids.append(i)

    def _daemons_detached(self, ids: list[str], via_child) -> None:
        """Orderly daemon retirement (the detach contract — no ranks
        re-classified): prune the subtree from the delivering link's
        membership and relay toward the root, which drops it from the
        placement order so no later launch targets a stopped daemon."""
        ids = [str(i) for i in ids]
        if via_child is not None:
            via_child.daemons.difference_update(ids)
        if self._parent_link is not None:
            try:
                self._parent_link.send_up("daemon-detached", ids)
            except OSError:
                pass  # parent gone: _parent_lost owns the teardown
            return
        with self._tree_lock:
            self._placement_ids = [d for d in self._placement_ids
                                   if d not in ids]

    def _handle_up(self, child, kind: str, payload: Any) -> None:
        """One upward frame from a child link.  An intermediate daemon
        relays job traffic toward the root; the root applies it."""
        if kind == "daemon-up":
            self._daemon_up(list(payload), via_child=child)
            return
        if kind == "detach":
            # orderly child shutdown: EOF that follows is not a death —
            # and the ROOT must unlearn the subtree (relayed as
            # daemon-detached so intermediate hops prune too; a stale
            # placement entry would strand the next launch's spawns)
            child.detached = True
            self._daemons_detached(sorted(child.daemons),
                                   via_child=None)
            return
        if kind == "daemon-detached":
            self._daemons_detached([str(d) for d in payload],
                                   via_child=child)
            return
        if kind == "daemon-down":
            if self._parent_link is not None:
                try:
                    self._parent_link.send_up(kind, payload)
                except OSError:
                    pass
                return
            self._daemons_lost([str(d) for d in payload])
            return
        if self._parent_link is not None:
            # io / exited / spawned climb to the root unchanged
            try:
                self._parent_link.send_up(kind, payload)
            except OSError:
                pass
            return
        if kind == "io":
            job = self._jobs.get(str(payload[0]))
            if job is not None:
                self._stream(job, ["io", int(payload[1]),
                                   str(payload[2]), payload[3]])
        elif kind == "exited":
            job = self._jobs.get(str(payload[0]))
            if job is not None:
                self._remote_exited(job, int(payload[1]),
                                    int(payload[2]))
        elif kind == "spawned":
            job = self._jobs.get(str(payload[0]))
            if job is not None:
                self._remote_spawned(job, {int(r): int(p)
                                           for r, p in
                                           payload[1].items()})
        else:
            mca_output.emit(
                _stream, "tree: unknown upward frame %r from %s — "
                "dropped", kind, child.id,
            )

    def _handle_down(self, kind: str, payload: Any) -> None:
        """One downward frame from the parent link (child side).
        Broadcast kinds re-broadcast to our own children FIRST (a
        kill that parks in its TERM grace locally must not delay the
        grandchild subtree by a whole level), then apply locally;
        routed kinds unwrap toward their target daemon."""
        if kind in ("gen", "nsdown", "fault", "kill", "kill-ranks",
                    "jobdone"):
            self._broadcast_down(kind, payload)
        if kind == "route":
            target, inner_kind, inner = str(payload[0]), \
                str(payload[1]), payload[2]
            if target == self.id:
                self._handle_down(inner_kind, inner)
                return
            link = self._link_for(target)
            if link is None:
                mca_output.emit(
                    _stream, "tree: no route to daemon %s for %r — "
                    "frame dropped", target, inner_kind,
                )
                return
            try:
                link.send_down("route", payload)
            except OSError:
                pass  # link death handled by its serving thread
            return
        if kind == "spawn":
            self._spawn_remote(payload)
        elif kind == "gen":
            if isinstance(self.store, dvmtree.RoutedStore):
                # the frame CARRIES the new generation: it raises the
                # leaf bucket's floor, so a fetch in flight across this
                # invalidation can never re-warm the cache with the
                # pre-bump incarnation's value
                gen = int(payload[1]) if len(payload) > 1 else None
                self.store.invalidate_ns(str(payload[0]), gen=gen)
        elif kind == "nsdown":
            if isinstance(self.store, dvmtree.RoutedStore):
                self.store.forget_ns(str(payload[0]))
        elif kind == "fault":
            job = self._jobs.get(str(payload[0]))
            if job is not None:
                self._notify_local_ranks(
                    job, [(int(r), int(rc)) for r, rc in payload[1]],
                    str(payload[2]))
        elif kind == "kill":
            job = self._jobs.get(str(payload[0]))
            if job is not None:
                self._teardown_job(job, rc=int(payload[1]))
        elif kind == "kill-ranks":
            self._kill_local_ranks(str(payload[0]),
                                   [int(r) for r in payload[1]])
        elif kind == "jobdone":
            job_id = str(payload[0])
            with self._lock:
                job = self._jobs.pop(job_id, None)
            if job is not None:
                _sweep_shm(job.session)
            if isinstance(self.store, dvmtree.RoutedStore):
                self.store.forget_ns(job_id)
        else:
            mca_output.emit(
                _stream, "tree: unknown downward frame %r — dropped",
                kind,
            )

    def _link_for(self, daemon_id: str):
        with self._tree_lock:
            for link in self._children.values():
                if daemon_id in link.daemons:
                    return link
        return None

    def _broadcast_down(self, kind: str, payload: Any) -> None:
        with self._tree_lock:
            links = list(self._children.values())
        for link in links:
            try:
                link.send_down(kind, payload)
            except OSError:
                pass  # link death handled by its serving thread

    def _send_tree(self, daemon_id: str, kind: str, payload: Any
                   ) -> None:
        """Targeted downward frame: handle locally or route through
        the child link whose subtree holds ``daemon_id``."""
        if daemon_id == self.id:
            self._handle_down(kind, payload)
            return
        link = self._link_for(daemon_id)
        if link is None:
            raise errors.InternalError(
                f"zprted tree: no route to daemon {daemon_id}")
        link.send_down("route", [daemon_id, kind, payload])

    def _child_lost(self, child) -> None:
        """A child link died without an orderly detach: every daemon in
        that subtree is gone, and with it every rank the subtree
        hosted.  The report climbs to the root, which classifies and
        floods."""
        subtree = sorted(child.daemons)
        mca_output.emit(
            _stream, "tree: child daemon %s LOST (subtree %s)",
            child.id, subtree,
        )
        if self._parent_link is not None:
            try:
                self._parent_link.send_up("daemon-down", subtree)
            except OSError:
                pass
            return
        self._daemons_lost(subtree)

    def _daemons_lost(self, ids: list[str]) -> None:
        """ROOT policy for a dead daemon subtree: drop it from
        placement, mark every rank it hosted failed
        (cause="daemon-tree"), flood the classification down the
        SURVIVING tree, and keep the exit accounting coherent — those
        ranks will never report ``exited``."""
        ids = set(ids)
        with self._tree_lock:
            self._placement_ids = [d for d in self._placement_ids
                                   if d not in ids]
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            with job.lock:
                victims = sorted(
                    r for r, d in job.placement.items()
                    if d in ids and r in job.remote_alive
                )
                for r in victims:
                    job.remote_alive.discard(r)
                    job.rcs[r] = -9
                    job.live -= 1
                    job.remote_pids.pop(r, None)
                last = job.live == 0
                stopping = job.stopping
                if victims and not stopping and job.fail_rc is None:
                    job.fail_rc = 137  # 128 + SIGKILL: the subtree died
            if not victims:
                continue
            if job.ft and not stopping:
                # _fault records the DAEMON_FAULT flightrec event
                self._fault(job, [(r, -9) for r in victims],
                            cause="daemon-tree")
            elif not stopping:
                flightrec.record(flightrec.DAEMON_FAULT, job=job.id,
                                 deaths=victims, cause="daemon-tree")
                self._stream(job, [
                    "note",
                    f"zprted: daemon subtree {sorted(ids)} died taking "
                    f"ranks {victims}; terminating job {job.id}\n"])
                self._teardown_job(job, rc=137)
                continue
            if last and not stopping:
                job.done.set()

    def _parent_lost(self) -> None:
        """This daemon's parent link died.  The root has (or will)
        declare this whole subtree dead — a daemon serving a store it
        can no longer reach must not keep ranks half-alive, so tear
        the local jobs down and stop."""
        if self.closed or self._stopping_tree:
            return
        mca_output.emit(
            _stream, "tree: parent daemon at %s lost — stopping this "
            "subtree", self._parent_addr,
        )
        self.stop()

    # -- root-store coherence hooks ---------------------------------------

    def _on_store_generation(self, ns: str, gen: int) -> None:
        self._broadcast_down("gen", [ns, int(gen)])

    def _on_store_destroy(self, ns: str) -> None:
        self._broadcast_down("nsdown", [ns])

    # -- metrics aggregation ----------------------------------------------

    def _metrics_ranks(self, ns: str) -> dict[int, dict]:
        """Per-rank published metrics of one namespace, staleness-
        stamped (``staleness_s``: daemon wall clock minus the
        snapshot's publish stamp), with each rank's flight-recorder
        window attached when one was published."""
        now = time.time()
        ranks: dict[int, dict] = {}
        for key, payload in self.store.lookup(ns, "metrics:").items():
            try:
                rank = int(key.rsplit(":", 1)[1])
                rec = dict(payload)
            except (ValueError, TypeError):
                continue  # foreign key shape: not a publisher's
            rec["staleness_s"] = max(0.0, now - float(rec.get("t", now)))
            ranks[rank] = rec
        for key, win in self.store.lookup(ns, "flightrec:").items():
            try:
                rank = int(key.rsplit(":", 1)[1])
            except ValueError:
                continue
            ranks.setdefault(rank, {})["flightrec"] = win
        return ranks

    def _metrics_view(self, ns: str, rank: int | None = None):
        """The ``metrics`` RPC: one rank's record, or the whole job —
        every rank's record plus the job-aggregated counter view
        (counters summed, watermarks maxed)."""
        ranks = self._metrics_ranks(ns)
        if not ranks:
            raise errors.ArgError(
                f"zprted metrics: no metrics published for job {ns!r} "
                "(launch with metrics=True / ZMPI_METRICS=1)")
        if rank is not None:
            if rank not in ranks:
                raise errors.ArgError(
                    f"zprted metrics: rank {rank} of job {ns!r} has "
                    "published nothing")
            return ranks[rank]
        aggregate: dict[str, int] = {}
        watermarks: set[str] = set()
        for rec in ranks.values():
            watermarks.update(rec.get("watermark") or ())
            for name, value in (rec.get("counters") or {}).items():
                if name in watermarks:
                    aggregate[name] = max(aggregate.get(name, 0), value)
                else:
                    aggregate[name] = aggregate.get(name, 0) + value
        return {"job": ns, "ranks": ranks, "aggregate": aggregate}

    @staticmethod
    def _prom_name(name: str) -> str:
        """Metric-name charset is [a-zA-Z0-9_:]; anything else (a
        templated family like ``comm_<name>_coll_calls`` instantiated
        with a dashed communicator name) collapses to ``_`` — one bad
        counter name must not invalidate the whole scrape body."""
        return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

    @staticmethod
    def _prom_label(value: str) -> str:
        """Label-value escaping per the text exposition format
        (backslash, double-quote, newline)."""
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def prometheus(self) -> str:
        """Text exposition of every namespace's published snapshots:
        ``zmpi_spc_<counter>{job="...",rank="..."} value`` plus a
        staleness gauge per rank — the ``GET /metrics`` body.  Samples
        are grouped by METRIC family (one contiguous block after each
        TYPE line, the exposition format's rule), not by rank — strict
        OpenMetrics-mode scrapers reject interleaved families."""
        # metric -> (kind, [sample lines]); insertion builds the rows,
        # emission walks families sorted
        families: dict[str, tuple[str, list[str]]] = {}

        def sample(metric: str, kind: str, labels: str, value) -> None:
            fam = families.setdefault(metric, (kind, []))
            fam[1].append(f"{metric}{labels} {value}")

        for ns in self.store.namespaces():
            ranks = self._metrics_ranks(ns)
            for rank in sorted(ranks):
                rec = ranks[rank]
                counters = rec.get("counters") or {}
                watermarks = set(rec.get("watermark") or ())
                labels = (f'{{job="{self._prom_label(ns)}",'
                          f'rank="{rank}"}}')
                for name in sorted(counters):
                    sample(f"zmpi_spc_{self._prom_name(name)}",
                           "gauge" if name in watermarks else "counter",
                           labels, counters[name])
                if "staleness_s" in rec:
                    sample("zmpi_metrics_age_seconds", "gauge", labels,
                           f"{rec['staleness_s']:.3f}")
        lines: list[str] = []
        for metric in sorted(families):
            kind, rows = families[metric]
            lines.append(f"# TYPE {metric} {kind}")
            lines.extend(rows)
        return "\n".join(lines) + ("\n" if lines else "")

    def _stream(self, job: _Job, payload: list) -> None:
        """One frame to the job's IOF client; a departed client must
        never wedge the daemon (output is dropped, children keep
        draining so their pipes never block)."""
        from ..pt2pt.tcp import _send_frame
        from ..utils import dss

        if job.io_broken or job.conn is None:
            # a child daemon's thin job mirror has no IOF client: its
            # lines ride the tree link up instead (_drain_iof)
            return
        try:
            with job.conn_lock:
                _send_frame(job.conn, dss.pack(payload))
        except OSError:
            job.io_broken = True

    # -- launch ----------------------------------------------------------

    def _rank_env(self, job: _Job, rank: int,
                  rejoin: "tuple[int, list[int]] | None" = None) -> dict:
        """The ZMPI_* contract of a daemon-hosted rank: PMIx-served
        modex (no coordinator address at all), the daemon's own address
        for the relaunch RPC, and the per-job session tag the /dev/shm
        sweep keys on.  Stale ZMPI_* from the daemon's OWN launch
        environment is scrubbed — a daemon started under zmpirun must
        not leak its launcher's contract into its children."""
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("ZMPI_")}
        env.update({
            "ZMPI_RANK": str(rank),
            "ZMPI_SIZE": str(job.size),
            "ZMPI_PMIX": f"{self.host}:{self.pmix.address[1]}/{job.id}",
            "ZMPI_DVM": f"{self.host}:{self.address[1]}",
            "ZMPI_JOB": job.id,
            "ZMPI_SESSION": job.session,
            # the rank parks one connection on OUR control port for its
            # whole life: daemon death severs it and the rank exits —
            # a dead daemon's subtree takes its ranks with it
            "ZMPI_LIFELINE": f"{self.host}:{self.address[1]}",
        })
        if job.ft:
            env["ZMPI_FT"] = "1"
        if job.elastic:
            # elastic membership contract: the endpoint universe is the
            # launch-time max, the CURRENT live set rides here (absent
            # ranks wire up as pre-acknowledged departures), and the
            # rank's elastic session skips resize events at or below
            # the one it was born into
            env["ZMPI_ELASTIC_LIVE"] = ",".join(
                str(r) for r in sorted(job.target))
            env["ZMPI_ELASTIC_SEEN"] = str(job.resize_seq - 1)
        if job.metrics:
            # the opt-in metrics plane: every rank of this job runs the
            # spc publisher against the resident store
            env["ZMPI_METRICS"] = "1"
        if job.trace:
            # the tracing plane rides the metrics publisher: every
            # rank arms its span recorder and ships trace:<job>:<rank>
            env["ZMPI_TRACE"] = "1"
        if rejoin is not None:
            # recovery-window metadata: the bumped namespace generation
            # and the whole batch of co-respawned ranks, so each
            # replacement reads its siblings' cards at the FRESH
            # generation (the corpse's old card must not satisfy it)
            gen, batch = rejoin
            env["ZMPI_REJOIN"] = "1"
            env["ZMPI_REJOIN_GEN"] = str(gen)
            env["ZMPI_REJOIN_RANKS"] = ",".join(str(r) for r in batch)
        pkg_root = _pkg_root()
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join(
                [pkg_root] + [p for p in parts if p])
        for name, value in job.mca or ():
            env[f"ZMPI_MCA_{name}"] = str(value)
        return env

    def _spawn_rank(self, job: _Job, rank: int,
                    rejoin: "tuple[int, list[int]] | None" = None
                    ) -> subprocess.Popen:
        p = subprocess.Popen(
            job.cmds[rank],
            env=self._rank_env(job, rank, rejoin=rejoin),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True,  # isolate from the daemon's signals
        )
        for stream, label in ((p.stdout, ""), (p.stderr, ":err")):
            t = threading.Thread(
                target=self._drain_iof, args=(job, rank, label, stream),
                daemon=True, name=f"dvm-iof-{job.id}-{rank}{label}",
            )
            t._dvm_proc = p  # the incarnation this drain serves
            t.start()
            job.drains.append(t)
        w = threading.Thread(
            target=self._watch_child, args=(job, rank, p),
            daemon=True, name=f"dvm-wait-{job.id}-{rank}",
        )
        w.start()
        job.watchers.append(w)
        return p

    def _drain_iof(self, job: _Job, rank: int, label: str, stream) -> None:
        for line in iter(stream.readline, ""):
            if self._parent_link is not None:
                try:
                    self._parent_link.send_up(
                        "io", [job.id, rank, label, line])
                except OSError:
                    break  # parent gone: _parent_lost tears us down
            else:
                self._stream(job, ["io", rank, label, line])
        stream.close()

    def _spawn_ranks(self, job: _Job, ranks: list[int],
                     rejoin: "tuple[int, list[int]] | None" = None
                     ) -> dict[int, int]:
        """Spawn ``ranks`` per the job's placement: local slots exec on
        THIS daemon, remote slots ride ``spawn`` frames down the tree
        to their hosts.  Returns the LOCAL pids (remote pids arrive as
        ``spawned`` frames)."""
        by_daemon: dict[str, list[int]] = {}
        for r in ranks:
            by_daemon.setdefault(
                job.placement.get(r, self.id), []).append(r)
        pids: dict[int, int] = {}
        local = by_daemon.pop(self.id, [])
        if local:
            with job.lock:
                for rank in local:
                    p = self._spawn_rank(job, rank, rejoin=rejoin)
                    job.procs[rank] = p
                    job.live += 1
                    pids[rank] = p.pid
        for daemon_id, rs in by_daemon.items():
            with job.lock:
                for r in rs:
                    if r not in job.remote_alive:
                        job.remote_alive.add(r)
                        job.live += 1
            try:
                # counted per remote spawn frame: the scaling gates
                # assert launch fan-out rides the tree, not root-direct
                spc.record("dvm_tree_routed_launches")
                self._send_tree(daemon_id, "spawn", {
                    "job": job.id, "size": job.size,
                    "cmds": {r: job.cmds[r] for r in rs},
                    "ranks": rs, "ft": job.ft,
                    "mca": [list(m) for m in (job.mca or [])],
                    "session": job.session, "metrics": job.metrics,
                    "trace": job.trace, "elastic": job.elastic,
                    "live": sorted(job.target),
                    "seen": job.resize_seq - 1,
                    "rejoin": None if rejoin is None
                    else [int(rejoin[0]), [int(r) for r in rejoin[1]]],
                })
            except errors.MpiError:
                # no route (the daemon died between placement and this
                # spawn): roll the phantom ranks back OUT of the live
                # accounting — ranks never spawned never report
                # exited, and job.live must still reach 0
                with job.lock:
                    for r in rs:
                        if r in job.remote_alive:
                            job.remote_alive.discard(r)
                            job.live -= 1
                    job.cv.notify_all()
                raise
        return pids

    def _spawn_remote(self, payload: dict) -> None:
        """Child side of a ``spawn`` frame: materialize (or extend) the
        thin local job mirror, exec the ranks, report their pids up."""
        job_id = str(payload["job"])
        size = int(payload["size"])
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                job = _Job(
                    job_id, size, [None] * size, bool(payload["ft"]),
                    [tuple(m) for m in (payload.get("mca") or [])],
                    str(payload["session"]), None, None,
                    metrics=bool(payload.get("metrics")),
                    trace=bool(payload.get("trace")),
                )
                self._jobs[job_id] = job
        job.elastic = bool(payload.get("elastic"))
        job.target = set(int(r) for r in (payload.get("live")
                                          or range(size)))
        job.resize_seq = int(payload.get("seen", -1)) + 1
        rejoin = payload.get("rejoin")
        rejoin = None if rejoin is None else (
            int(rejoin[0]), [int(r) for r in rejoin[1]])
        ranks = [int(r) for r in payload["ranks"]]
        pids: dict[int, int] = {}
        with job.lock:
            for rank in ranks:
                job.cmds[rank] = [str(a) for a in
                                  payload["cmds"][rank]]
                old = job.procs.get(rank)
                if old is not None and old.poll() is None:
                    # a respawn over a wedged local incarnation: the
                    # declared-dead process is killed first (the PRRTE
                    # contract the root applies to ITS local ranks too)
                    try:
                        os.killpg(os.getpgid(old.pid), signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
                if old is not None \
                        and not getattr(old, "_dvm_accounted", False):
                    old._dvm_accounted = True
                    job.superseded.setdefault(rank, []).append(old)
                p = self._spawn_rank(job, rank, rejoin=rejoin)
                job.procs[rank] = p
                pids[rank] = p.pid
        if self._parent_link is not None:
            try:
                self._parent_link.send_up("spawned", [job_id, pids])
            except OSError:
                pass

    def _remote_spawned(self, job: _Job, pids: dict[int, int]) -> None:
        """ROOT accounting for a child's spawn report: remember the
        pids, wake respawn/resize waiters."""
        with job.lock:
            job.remote_pids.update(pids)
            for r in pids:
                if r not in job.remote_alive:
                    job.remote_alive.add(r)
                    job.live += 1
            job.cv.notify_all()

    def _remote_exited(self, job: _Job, rank: int, rc: int) -> None:
        """ROOT accounting for a remote rank's death (OS truth riding
        up the tree), then the same policy fork the local watcher
        takes: ft jobs flood the fault, non-ft jobs abort."""
        with job.lock:
            if rank not in job.remote_alive:
                return  # stale report (a superseded incarnation)
            job.remote_alive.discard(rank)
            job.remote_pids.pop(rank, None)
            job.rcs[rank] = rc
            job.live -= 1
            last = job.live == 0
            stopping = job.stopping
            if rc != 0 and not stopping and job.fail_rc is None \
                    and not job.retired(rank):
                job.fail_rc = 128 - rc if rc < 0 else rc
            job.cv.notify_all()
        self._exit_policy(job, rank, rc, last, stopping)

    @staticmethod
    def _resolve_cmd(argv: list) -> list[str]:
        argv = [str(a) for a in argv]
        return [sys.executable] + argv if argv[0].endswith(".py") \
            else argv

    def _handle_launch(self, spec: dict, conn, conn_lock) -> None:
        if self._parent_link is not None:
            raise errors.ArgError(
                "zprted: launch must target the ROOT daemon of the "
                "tree (this zprted runs with --parent; respawn/resize/"
                "stat relay up, launch does not)")
        apps = spec.get("apps")
        if apps:
            # MPMD into the VM: consecutive rank blocks per app context
            # (mixed C/Python jobs share the store-served wire-up)
            if any(int(cnt) < 1 for cnt, _ in apps):
                raise errors.ArgError(
                    "zprted launch: every app context needs n >= 1")
            n = sum(int(cnt) for cnt, _ in apps)
            cmds: list[list[str]] = []
            for cnt, argv in apps:
                cmds.extend([self._resolve_cmd(argv)] * int(cnt))
        else:
            n = int(spec["n"])
            if n < 1:
                raise errors.ArgError("zprted launch: n must be >= 1")
            cmds = [self._resolve_cmd(spec["argv"])] * n
        max_size = int(spec.get("max_size") or n)
        if max_size < n:
            raise errors.ArgError(
                f"zprted launch: max_size {max_size} below n {n}")
        elastic = max_size > n
        if elastic and not spec.get("ft"):
            raise errors.ArgError(
                "zprted launch: an elastic job (max_size > n) grows "
                "and shrinks through the FT_JOIN/BYE machinery — it "
                "requires ft=True")
        if elastic and apps:
            raise errors.ArgError(
                "zprted launch: elastic jobs are single-app (grown "
                "slots reuse the one argv)")
        if elastic:
            # the C shim speaks the store verbs but not the resize
            # event stream (ElasticSession is the worker-side half of
            # the contract) — an elastic C job would wedge its modex
            # fence against the absent slots.  "Python" means a .py
            # argv (resolved onto this interpreter) or an explicit
            # python interpreter spelling — not interpreter-path
            # equality, which would reject venv launches.
            head = os.path.basename(cmds[0][0])
            if cmds[0][0] != sys.executable \
                    and not head.startswith("python"):
                raise errors.ArgError(
                    "zprted launch: elastic jobs are Python-only "
                    "(the worker must run an "
                    "ft.recovery.ElasticSession)")
        if elastic:
            cmds = cmds + [cmds[0]] * (max_size - n)
        timeout = spec.get("timeout")
        priority = int(spec.get("priority") or 0)
        policy = str(spec.get("placement")
                     or mca_var.get("dvm_placement", "pack"))
        # admission is a QUEUE, not a convoy: the ticket blocks here —
        # streaming [queued, pos] frames so the client knows where it
        # stands — until the policy order and the concurrency cap both
        # admit it; a dead client's ticket is reaped (conn_alive), and
        # only then does setup() serialize the actual job setup
        ticket = self._admission.enqueue(priority)
        try:
            wait_s = self._admission.admit(
                ticket,
                alive=lambda: pmix_mod.conn_alive(conn),
                on_position=lambda pos: self._queued_frame(
                    conn, conn_lock, pos))
            if wait_s is None:
                mca_output.verbose(
                    1, _stream, "launch: queued client died — ticket "
                    "reaped, launch dropped")
                return
            if ticket.was_queued:
                spc.record("dvm_jobs_queued")
                spc.record("dvm_queue_wait_ms", int(wait_s * 1000))
            with self._admission.setup():
                with self._lock:
                    job_id = f"job{next(self._job_ids)}"
                    job = _Job(
                        job_id, max_size, cmds, bool(spec.get("ft")),
                        [tuple(m) for m in (spec.get("mca") or [])],
                        f"{self.session}_{job_id}",
                        conn, conn_lock,
                        metrics=bool(spec.get("metrics")),
                        # trace implies metrics (the publisher ships
                        # the span buffers): a trace-only launch gets
                        # both
                        trace=bool(spec.get("trace")),
                    )
                    if job.trace:
                        job.metrics = True
                    job.elastic = elastic
                    job.target = set(range(n))
                    self._jobs[job_id] = job
                # the namespace IS the jobid: ranks modex through the
                # resident store with zero per-job rendezvous
                # infrastructure.  Its size is the INITIAL live count
                # (the modex fence barriers the starters; grown ranks
                # rejoin without fencing).
                try:
                    self.store.ensure_ns(job_id, n)
                    with self._tree_lock:
                        daemons = list(self._placement_ids)
                    with self._lock:
                        live = [j for j in self._jobs.values()
                                if j is not job
                                and not j.done.is_set()]
                        busy: dict[str, int] = {}
                        for j in live:
                            for d in set(j.placement.values()):
                                busy[d] = busy.get(d, 0) + 1
                    placement, fell_back = dvmtree.place_job(
                        sorted(job.target), daemons, busy, policy)
                    if fell_back:
                        spc.record("dvm_placement_fallbacks")
                        self._stream(job, [
                            "note",
                            "zprted: exclusive placement unavailable "
                            "(no free daemon) — falling back to "
                            "spread\n"])
                    job.placement = placement
                    job.exclusive = policy == "exclusive" \
                        and not fell_back
                    # the per-job audit: prove this tenant's runtime
                    # state disjoint from every live co-tenant's
                    # before a single rank spawns
                    dvmtree.audit_placement(
                        {"id": job.id, "session": job.session,
                         "daemons": sorted(set(placement.values())),
                         "exclusive": job.exclusive},
                        [{"id": j.id, "session": j.session,
                          "daemons": sorted(set(
                              j.placement.values())),
                          "exclusive": j.exclusive}
                         for j in live])
                    self._stream(job, ["job", job_id])
                    self._spawn_ranks(job, sorted(job.target),
                                      rejoin=None)
                except errors.MpiError:
                    # half-spawned job (a daemon died between
                    # placement and its spawn frame) or a failed
                    # audit: the already-started ranks, the namespace,
                    # and the _jobs entry must not leak for the
                    # daemon's lifetime
                    self._teardown_job(job, rc=1)
                    self._finalize_job(job)
                    raise
                spc.record("dvm_jobs_launched")
            self._run_admitted(job, job_id, timeout)
        finally:
            # the one release covers every exit path: a finished job
            # frees its concurrency slot, a failed/errored launch its
            # ticket — either way the queue wakes
            self._admission.release(ticket)

    def _queued_frame(self, conn, conn_lock, pos: int) -> None:
        """One ``[queued, position]`` frame to a still-waiting launch
        client (no _Job exists yet, so this bypasses _stream).  Old
        clients ignore unknown stream kinds — the frame is additive."""
        from ..pt2pt.tcp import _send_frame
        from ..utils import dss

        try:
            with conn_lock:
                _send_frame(conn, dss.pack(["queued", int(pos)]))
        except OSError:
            pass  # admit()'s alive() poll reaps the dead client

    def _run_admitted(self, job: _Job, job_id: str,
                      timeout) -> None:
        # a job with no deadline of its own still may not park this
        # handler forever on a wedged rank set
        timeout = timeout if timeout \
            else float(mca_var.get("dvm_job_timeout", 600.0))
        if not job.done.wait(timeout):
            self._stream(job, ["note",
                               f"zprted: job {job_id} timeout after "
                               f"{timeout}s; killing it\n"])
            self._teardown_job(job, rc=124)
        # IOF flushes before the exit frame: each drain exits at its
        # stream's EOF, which the children's deaths guarantee.  The
        # joins share ONE generous deadline (_IOF_DRAIN_GRACE) instead
        # of a short per-thread bound: a drain starved by scheduler
        # load must not lose a rank's final lines to a client that
        # stops reading at the exit frame (the finalize-skew flake);
        # a drain STILL live past the deadline means a leaked
        # grandchild holds a dead child's pipe — reported loudly,
        # never as silent truncation.
        drain_deadline = time.monotonic() + _IOF_DRAIN_GRACE
        for t in list(job.drains):
            t.join(timeout=max(0.0, drain_deadline - time.monotonic()))
        straggler = [t.name for t in job.drains if t.is_alive()]
        if straggler:
            self._stream(job, [
                "note",
                f"zprted: IOF drain(s) {straggler} still live "
                f"{_IOF_DRAIN_GRACE:.0f}s after job {job.id} ended "
                "(a child's pipe is held open — leaked grandchild?); "
                "trailing output may be truncated\n"])
        with job.lock:
            if job.stopping:
                # abort/timeout teardown: the first failure (or 124) is
                # the job's code — the zmpirun contract
                rc = int(job.fail_rc or 0)
            else:
                # ran to completion: judge each rank by its LATEST
                # incarnation — a respawned-over corpse's exit status
                # is recovery history, not a job failure, and a
                # RETIRED elastic slot's exit (even the escalation
                # ladder's SIGTERM) was a requested departure
                bad = [c for r, c in job.rcs.items()
                       if c != 0 and not job.retired(r)]
                rc = (128 - bad[0] if bad[0] < 0 else int(bad[0])) \
                    if bad else 0
        self._stream(job, ["exit", rc])
        self._finalize_job(job)

    # -- child watching / fault events -----------------------------------

    def _watch_child(self, job: _Job, rank: int,
                     p: subprocess.Popen) -> None:
        """One BLOCKING waitpid per child — the daemon's failure source
        is the OS, not a timeout.  On a tree CHILD the exit climbs to
        the root (which owns accounting and policy); the root and the
        single-daemon shape account locally."""
        rc = p.wait()
        with job.lock:
            # exit accounting happens EXACTLY once per proc: here, or in
            # the respawn RPC's corpse-adoption path if it won the race
            if getattr(p, "_dvm_accounted", False):
                return
            p._dvm_accounted = True
            current = job.procs.get(rank) is p
            if self._parent_link is None:
                if current:
                    job.rcs[rank] = rc
                job.live -= 1
                last = job.live == 0
                stopping = job.stopping
                if current and rc != 0 and not stopping \
                        and job.fail_rc is None \
                        and not job.retired(rank):
                    # signal death → 128+sig (the shell convention)
                    job.fail_rc = 128 - rc if rc < 0 else rc
                job.cv.notify_all()
        if self._parent_link is not None:
            if current:
                # flush THIS incarnation's IOF drains before reporting
                # the exit: the tree link is FIFO, so once the tails
                # are on the wire the root streams them before it can
                # account the death and emit the job's exit frame (a
                # dead child's pipes are at EOF — the join waits out
                # scheduler starvation under the same shared grace as
                # the root's exit-frame joins, never a live stream)
                drain_deadline = time.monotonic() + _IOF_DRAIN_GRACE
                for t in list(job.drains):
                    if getattr(t, "_dvm_proc", None) is p:
                        t.join(timeout=max(
                            0.0, drain_deadline - time.monotonic()))
                try:
                    self._parent_link.send_up(
                        "exited", [job.id, rank, int(rc)])
                except OSError:
                    pass  # parent gone: _parent_lost tears us down
            return
        if current:
            self._exit_policy(job, rank, rc, last, stopping)
        elif last and not stopping:
            job.done.set()

    def _exit_policy(self, job: _Job, rank: int, rc: int, last: bool,
                     stopping: bool) -> None:
        """The fork every rank death takes at the accounting daemon:
        ft jobs flood an authoritative fault event (death is a
        recovery input, the job keeps running); non-ft jobs abort
        (MPI_Abort semantics, the zmpirun contract)."""
        if rc != 0 and not stopping:
            norm = 128 - rc if rc < 0 else rc
            if job.ft:
                # authoritative fault event: the survivors learn NOW,
                # from OS truth, not after a heartbeat window
                self._fault(job, [(rank, rc)], cause="daemon")
            else:
                self._stream(job, ["note",
                                   f"zprted: rank {rank} exited with "
                                   f"code {norm}; terminating job "
                                   f"{job.id}\n"])
                self._teardown_job(job, rc=norm)
                return
        if last and not stopping:
            job.done.set()

    def _fault(self, job: _Job, deaths: list, cause: str = "daemon"
               ) -> None:
        """Authoritative fault event, routed BOTH ways: record it,
        notify the survivors THIS daemon hosts, and flood the
        classification down every child link — each daemon of the tree
        notifies its own ranks, so the whole job learns without the
        root dialing every survivor socket itself."""
        spc.record("dvm_fault_events", len(deaths))
        flightrec.record(flightrec.DAEMON_FAULT, job=job.id,
                         deaths=[int(r) for r, _ in deaths],
                         cause=cause)
        mca_output.verbose(
            2, _stream, "job %s: rank(s) %s died (cause=%s); flooding "
            "fault event", job.id, [r for r, _ in deaths], cause,
        )
        self._notify_local_ranks(job, deaths, cause)
        self._broadcast_down(
            "fault",
            [job.id, [[int(r), int(rc)] for r, rc in deaths], cause])

    def _notify_local_ranks(self, job: _Job, deaths: list,
                            cause: str) -> None:
        """FT_DVM_CID to every survivor THIS daemon hosts, addressed
        from the name-served cards (leaf-cached on a tree child — the
        flood costs the root nothing per rank)."""
        from ..pt2pt.tcp import _send_frame
        from ..ft import ulfm
        from ..utils import dss

        dead = {int(r) for r, _ in deaths}
        hello = dss.pack(["d", -1])
        frame = dss.pack(-1, 0, ulfm.FT_DVM_CID, 0,
                         [[int(r), int(rc), str(cause)]
                          for r, rc in deaths])

        def notify(rank):
            # the card lookup rides INSIDE the per-rank thread: one
            # not-yet-modexed survivor's get timeout must not delay
            # the already-modexed survivors' notifications
            try:
                card = self.store.get(job.id, f"card:{rank}",
                                      timeout=0.25)
            except errors.MpiError:
                return  # not modexed yet: nothing to notify
            try:
                sock = socket.create_connection(
                    (card[0], int(card[1])), 2.0)
            except OSError:
                return  # also dying: its own watcher's course
            try:
                _send_frame(sock, hello)
                _send_frame(sock, frame)
            except OSError:
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        # one short-lived thread per survivor: the whole point of this
        # event is beating the heartbeat window, so a co-dying rank's
        # connect timeout (or a not-yet-modexed card) must not serialize
        # ahead of the survivors still waiting to hear
        for r in job.alive_ranks():
            if r in dead:
                continue
            threading.Thread(
                target=notify, args=(r,),
                daemon=True, name=f"dvm-fault-{job.id}-{r}",
            ).start()

    def _kill_local_ranks(self, job_id: str, ranks: list[int],
                          sig=signal.SIGTERM) -> None:
        """Signal THIS daemon's procs for ``ranks`` (retire
        escalation / tree-wide teardown helpers)."""
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            return
        with job.lock:
            procs = [job.procs[r] for r in ranks if r in job.procs]
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), sig)
                except (OSError, ProcessLookupError):
                    pass

    def _handle_respawn(self, job_id: str, ranks: list[int]) -> list[int]:
        """The relaunch RPC: exec a fresh OS process per victim.  ONE
        generation bump covers the whole batch — N replacements of one
        recovery window publish their fresh cards under the same tag
        and FT_JOIN the same name-served job.  On a tree, each victim
        respawns on the daemon that PLACED it: local slots exec here,
        remote slots ride spawn frames down and their pids ride back
        up."""
        job = self._job(job_id)
        if job.done.is_set():
            raise errors.ArgError(
                f"zprted: job {job_id} already completed")
        if not ranks:
            return []
        batch = sorted(set(int(r) for r in ranks))
        # respawn IS job setup: it reads placement/target and ships
        # membership env (ZMPI_ELASTIC_*) — riding its job's admission
        # (the setup lock directly, never the launch queue) keeps it
        # from observing a resize's half-applied state, and a QUEUED
        # launch can never interleave it (tickets hold no lock)
        with self._admission.setup():
            return self._respawn_admitted(job, job_id, batch)

    def _respawn_admitted(self, job: _Job, job_id: str,
                          batch: list[int]) -> list[int]:
        # validate the WHOLE batch before spawning any of it: a bad
        # rank must not leave a half-respawned recovery window
        for rank in batch:
            if not 0 <= rank < job.size:
                raise errors.ArgError(
                    f"zprted respawn: rank {rank} outside job "
                    f"{job_id} (size {job.size})")
            if job.elastic and rank not in job.target:
                raise errors.ArgError(
                    f"zprted respawn: rank {rank} is outside job "
                    f"{job_id}'s live membership — a retired slot "
                    "grows back through the resize RPC")
        local = [r for r in batch
                 if job.placement.get(r, self.id) == self.id]
        remote = [r for r in batch if r not in local]
        with job.lock:
            for rank in local:
                old = job.procs.get(rank)
                if old is not None and old.poll() is None:
                    # a victim the survivors AGREED dead whose OS
                    # process still exists is wedged (deadlock,
                    # SIGSTOP, half-dead) — the PRRTE contract kills
                    # the declared-dead incarnation before respawning,
                    # it never refuses the recovery
                    try:
                        os.killpg(os.getpgid(old.pid), signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
                    try:
                        # zlint: disable=ZL002 -- the respawn batch is atomic under job.lock by design (generation window + exit accounting); the reap of a SIGKILLed corpse is bounded to 5 s
                        old.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        raise errors.InternalError(
                            f"zprted respawn: wedged rank {rank} of "
                            f"{job_id} survived SIGKILL")
            for rank in local:
                old = job.procs.get(rank)
                if old is not None:
                    if not getattr(old, "_dvm_accounted", False):
                        # adopt the corpse's exit before its watcher
                        # does: the once-per-proc accounting contract
                        old._dvm_accounted = True
                        job.rcs[rank] = old.returncode
                        job.live -= 1
                    job.superseded.setdefault(rank, []).append(old)
            for rank in batch:
                # the replacement's exit judges the slot from here on —
                # and a wedged REMOTE incarnation's stale pid must not
                # satisfy the confirmation wait below (its daemon
                # SIGKILLs it without an exited report)
                job.rcs.pop(rank, None)
                if rank in remote:
                    job.remote_pids.pop(rank, None)
        gen = self.store.bump_generation(job_id)
        local_pids = self._spawn_ranks(job, batch, rejoin=(gen, batch))
        self._await_remote_pids(job, remote, "respawn")
        spc.record("dvm_respawns", len(batch))
        # root-side respawn event: the soak harness's MTTR postmortem
        # reads the daemon's own flight recorder, not a rank's
        flightrec.record(flightrec.RESPAWN, job=job_id,
                         ranks=[int(r) for r in batch],
                         generation=int(gen))
        with job.lock:
            return [local_pids.get(r, job.remote_pids.get(r))
                    for r in batch]

    def _await_remote_pids(self, job: _Job, ranks: list[int],
                           what: str, timeout: float = 20.0) -> None:
        """Block until every remote rank's hosting daemon confirmed
        its spawn (the ``spawned`` frame repopulates
        ``job.remote_pids``)."""
        if not ranks:
            return
        deadline = time.monotonic() + timeout
        with job.cv:
            while not all(r in job.remote_pids for r in ranks):
                left = deadline - time.monotonic()
                if left <= 0:
                    missing = [r for r in ranks
                               if r not in job.remote_pids]
                    raise errors.InternalError(
                        f"zprted {what}: daemons hosting ranks "
                        f"{missing} never confirmed the spawn")
                job.cv.wait(min(left, 0.25))

    # -- elastic resize ---------------------------------------------------

    def _publish_resize(self, job: _Job, seq: int, kind: str,
                        ranks: list[int], gen: int) -> None:
        """One resize event into the job's namespace — the worker-side
        elastic sessions consume the ``resize:<seq>`` stream in order
        (rank 0 of the live endpoint reads it and broadcasts, so the
        whole job applies each event at one loop boundary)."""
        self.store.put(job.id, -1, f"resize:{seq}", {
            "seq": int(seq), "kind": str(kind),
            "ranks": [int(r) for r in ranks],
            "live": sorted(job.target), "generation": int(gen),
        })
        self.store.commit(job.id, -1)

    def _handle_resize(self, job_id: str, new_n: int) -> dict:
        """The resize RPC: grow spawns fresh ranks into a bumped
        store generation (they FT_JOIN the live job exactly like a
        recovery window's replacements); shrink retires the highest
        live ranks through the orderly-BYE path (they observe the
        event, close, and exit 0).  Rides the admission lock: a resize
        is job setup and may not interleave with a launch."""
        job = self._job(job_id)
        if not job.ft:
            raise errors.ArgError(
                "zprted resize: only ft jobs resize (grow rides "
                "FT_JOIN, shrink rides the orderly BYE)")
        if job.done.is_set():
            raise errors.ArgError(
                f"zprted: job {job_id} already completed")
        new_n = int(new_n)
        if not 1 <= new_n <= job.size:
            raise errors.ArgError(
                f"zprted resize: size {new_n} outside 1..{job.size} "
                "(the launch max_size)")
        with self._admission.setup():
            with job.lock:
                target = set(job.target)
            delta = new_n - len(target)
            if delta == 0:
                return {"job": job_id, "size": new_n, "grown": [],
                        "retired": [], "seq": None,
                        "generation": self.store.generation(job_id)}
            with job.lock:
                seq = job.resize_seq
                job.resize_seq = seq + 1
            sp = ztrace.begin(ztrace.RESIZE, -1, job=job_id,
                              delta=delta) if ztrace.active else None
            if delta > 0:
                grown = sorted(r for r in range(job.size)
                               if r not in target)[:delta]
                # ONE generation bump for the whole grow window (the
                # respawn-batch contract): every new rank publishes its
                # card under the fresh tag, and the bump rides the tree
                # links down as cache invalidations
                gen = self.store.bump_generation(job_id)
                with self._tree_lock:
                    daemons = list(self._placement_ids)
                with job.lock:
                    job.target |= set(grown)
                    # fresh placement over the CURRENT daemon list —
                    # a re-grown slot must not inherit a placement
                    # entry pointing at a daemon that since detached —
                    # restricted to the job's CLAIMED subtree while
                    # any of it survives: a grown slot of an
                    # exclusive/spread tenant must not land on a
                    # co-tenant's daemons
                    prev_placement = {r: job.placement.get(r)
                                      for r in grown}
                    claimed = set(job.placement.values())
                    pool = [d for d in daemons if d in claimed] \
                        or daemons
                    for i, r in enumerate(grown):
                        job.placement[r] = pool[i % len(pool)]
                try:
                    local_pids = self._spawn_ranks(job, grown,
                                                   rejoin=(gen, grown))
                    self._await_remote_pids(
                        job, [r for r in grown
                              if r not in local_pids],
                        "resize grow")
                except errors.MpiError:
                    # a failed grow must not poison the RUNNING job:
                    # restore the pre-grow membership and seq before
                    # re-raising, so survivors never see (and block
                    # on) an event whose ranks will never FT_JOIN.
                    # The event publishes only AFTER confirmation; the
                    # spare generation bump is a harmless cache
                    # invalidation.
                    with job.lock:
                        job.target -= set(grown)
                        for r, d in prev_placement.items():
                            if d is None:
                                job.placement.pop(r, None)
                            else:
                                job.placement[r] = d
                        job.resize_seq = seq
                    raise
                self._publish_resize(job, seq, "grow", grown, gen)
                retired: list[int] = []
            else:
                retired = sorted(target)[delta:]
                gen = self.store.generation(job_id)
                with job.lock:
                    job.target -= set(retired)
                self._publish_resize(job, seq, "shrink", retired, gen)
                self._await_retire(job, retired)
                grown = []
            spc.record("dvm_resizes")
            flightrec.record(
                flightrec.RESIZE, job=job_id,
                kind="grow" if delta > 0 else "shrink",
                ranks=grown or retired, generation=int(gen))
            if sp is not None:
                sp.end(generation=int(gen), delta=delta)
        mca_output.verbose(
            1, _stream, "job %s resized to %d (%s %s, generation %d)",
            job_id, new_n, "grew" if delta > 0 else "retired",
            grown or retired, gen,
        )
        return {"job": job_id, "size": new_n, "grown": grown,
                "retired": retired, "seq": seq,
                "generation": int(gen)}

    def _await_retire(self, job: _Job, ranks: list[int],
                      grace: float = 15.0) -> None:
        """Retiring ranks exit THEMSELVES: the elastic session observes
        the shrink event at its next loop boundary, says an orderly
        BYE, and exits 0.  Halfway through the grace window the daemon
        escalates to SIGTERM; a rank that still won't leave is noted
        loudly and left to the accounting (a later grow over its slot
        SIGKILLs it like any wedged incarnation)."""
        deadline = time.monotonic() + grace
        escalated = False
        while True:
            with job.lock:
                waiting = [
                    r for r in ranks
                    if r in job.remote_alive
                    or (r in job.procs
                        and job.procs[r].poll() is None)
                ]
            if not waiting:
                return
            now = time.monotonic()
            if now > deadline:
                self._stream(job, [
                    "note",
                    f"zprted: resize: retiring ranks {waiting} did "
                    f"not exit within {grace}s\n"])
                return
            if not escalated and now > deadline - grace / 2:
                escalated = True
                self._kill_local_ranks(job.id, waiting)
                self._broadcast_down("kill-ranks", [job.id, waiting])
            with job.cv:
                job.cv.wait(0.1)

    # -- teardown ---------------------------------------------------------

    def _teardown_job(self, job: _Job, rc: int) -> None:
        with job.lock:
            job.stopping = True
            if job.fail_rc is None or rc == 124:
                job.fail_rc = rc
            procs = list(job.procs.values())
            remote = bool(job.remote_alive)
        if self._parent_link is None and remote:
            # tree-wide teardown: every daemon kills its local procs;
            # their exits ride up and drain remote_alive
            self._broadcast_down("kill", [job.id, int(rc)])
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except (OSError, ProcessLookupError):
                    pass
        grace_end = time.monotonic() + _TERM_GRACE
        for p in procs:
            try:
                p.wait(timeout=max(0.0, grace_end - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
                p.wait()
        if self._parent_link is None and remote:
            deadline = time.monotonic() + 2 * _TERM_GRACE
            with job.cv:
                while job.remote_alive \
                        and time.monotonic() < deadline:
                    job.cv.wait(0.1)
        job.done.set()

    def _finalize_job(self, job: _Job) -> None:
        """End-of-job hygiene: reap superseded corpses, drop the
        namespace (the destroy hook broadcasts the invalidation), tell
        the tree the job is over, sweep the job's /dev/shm artifacts
        (killed ranks never unlink their own rings)."""
        with job.lock:
            leftovers = [p for ps in job.superseded.values() for p in ps]
        for p in leftovers:
            try:
                p.wait(timeout=1.0)
            except subprocess.TimeoutExpired:
                pass
        if self._parent_link is None:
            # only the ROOT owns the namespace lifecycle (a stopping
            # child must not destroy a job still running elsewhere);
            # the destroy hook broadcasts the invalidation
            self.store.destroy_ns(job.id)
            self._broadcast_down("jobdone", [job.id])
        elif isinstance(self.store, dvmtree.RoutedStore):
            self.store.forget_ns(job.id)
        _sweep_shm(job.session)
        with self._lock:
            self._jobs.pop(job.id, None)

    def stop(self) -> None:
        """Orderly daemon shutdown: kill every live job, drop the store,
        close both listeners (the shared shutdown ladder), sweep the
        session."""
        if self.closed:
            return
        self._stopping_tree = True
        # queued launches first: every waiter raises (the client gets
        # an err frame) instead of parking on a queue nobody will
        # ever advance again
        self._admission.close()
        with self._lock:
            jobs = list(self._jobs.values())
        # local jobs die BEFORE the goodbye: their exits ride the
        # still-open parent link, so the root's accounting drains
        # instead of stranding the ranks in remote_alive forever
        for job in jobs:
            self._teardown_job(job, rc=143)
            self._finalize_job(job)
        if self._parent_link is not None:
            # the watchers' exited frames must be ON the wire before
            # the goodbye (the procs are dead, so the joins are
            # bounded hygiene, not waits on live children)
            for job in jobs:
                for w in job.watchers:
                    if w is not threading.current_thread():
                        w.join(timeout=5.0)
            # orderly goodbye before the listener closes: the parent
            # must not classify this shutdown as a lost subtree, and
            # the root unlearns this daemon from placement
            self._parent_link.detach()
        if self.metrics_http is not None:
            self.metrics_http.close()
        self.pmix.close()
        super().close()
        _sweep_shm(self.session)
        self._stop_evt.set()

    def close(self) -> None:
        """The RPC-scaffold name for :meth:`stop` — a Dvm closed like a
        bare server still tears its jobs down."""
        self.stop()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the daemon is stopped (RPC or signal)."""
        return self._stop_evt.wait(timeout)


class DvmClient:
    """Client handle to a running daemon — ``zmpirun --dvm`` and the
    recovery pipeline's relaunch RPC both speak through this."""

    def __init__(self, address: tuple[str, int] | str,
                 timeout: float = 30.0):
        self.address = pmix_mod.parse_addr(address)
        self._timeout = timeout
        self.last_job_id: str | None = None
        #: last [queued, pos] frame seen by launch() — None until the
        #: daemon actually parks the launch in its admission queue
        self.last_queue_position: int | None = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        try:
            self._sock.connect(self.address)
        except OSError as e:
            self._sock.close()
            raise errors.InternalError(
                f"zprted: no daemon at {self.address}: {e}"
            ) from e

    def _call(self, req: list, wait: float | None = None) -> Any:
        from ..pt2pt.tcp import _recv_frame, _send_frame
        from ..utils import dss

        self._sock.settimeout((wait or 0.0) + self._timeout)
        try:
            _send_frame(self._sock, dss.pack(req))
            frame = _recv_frame(self._sock)
        except OSError as e:
            raise errors.InternalError(
                f"zprted: daemon connection lost mid-{req[0]}: {e}"
            ) from e
        if frame is None:
            raise errors.InternalError(
                f"zprted: daemon closed the connection mid-{req[0]}")
        [status, value] = dss.unpack(frame)[0]
        if status != "ok":
            raise errors.InternalError(f"zprted {req[0]}: {value}")
        return value

    def launch(self, n: int, argv: list[str] | None = None,
               mca: list | None = None, ft: bool = False,
               timeout: float | None = None, tag_output: bool = True,
               stdout=None, stderr=None, metrics: bool = False,
               trace: bool = False, max_size: int | None = None,
               apps: list | None = None, priority: int = 0,
               placement: str | None = None) -> int:
        """Launch an n-rank job into the resident VM; streams its IOF
        and returns the job exit code (the ``zmpirun`` surface, minus
        the per-job launcher).  ``max_size`` (> n) makes the job
        ELASTIC: the endpoint universe is max_size, ranks n..max_size-1
        start absent, and the ``resize`` RPC grows/shrinks the live
        membership while the job runs.  ``apps`` replaces ``argv`` for
        MPMD into the VM: ``[(n1, argv1), (n2, argv2), ...]`` launches
        consecutive rank blocks per context (mixed C/Python jobs share
        the store-served wire-up); ``n`` is ignored when given.
        ``priority`` orders this launch in the daemon's admission
        queue under dvm_admission_policy=priority (higher first);
        ``placement`` overrides the daemon's dvm_placement policy for
        this job (pack/spread/exclusive).  While the launch waits in
        the admission queue the daemon streams ``[queued, pos]``
        frames — mirrored into :attr:`last_queue_position` and noted
        on ``stderr``."""
        from ..pt2pt.tcp import _recv_frame, _send_frame
        from ..utils import dss

        stdout = stdout if stdout is not None else sys.stdout
        stderr = stderr if stderr is not None else sys.stderr
        if (argv is None) == (apps is None):
            raise errors.ArgError(
                "zprted launch: pass exactly one of argv / apps")
        spec = {"n": int(n),
                "argv": [str(a) for a in (argv or [])],
                "apps": None if apps is None else
                [[int(cnt), [str(a) for a in ctx]]
                 for cnt, ctx in apps],
                "mca": [list(m) for m in (mca or [])], "ft": bool(ft),
                "timeout": timeout, "metrics": bool(metrics),
                "trace": bool(trace),
                "max_size": None if max_size is None else int(max_size),
                "priority": int(priority),
                "placement": None if placement is None
                else str(placement)}
        self.last_queue_position = None
        # no client-imposed deadline without an explicit job timeout:
        # the daemon enforces its own (tunable) dvm_job_timeout and
        # ALWAYS sends the exit frame, and a daemon crash surfaces as
        # EOF/reset — a hard-coded recv timeout here would desync from
        # a raised server-side limit and abandon a healthy job's IOF
        self._sock.settimeout(timeout + 30.0 if timeout else None)
        try:
            _send_frame(self._sock, dss.pack(["launch", spec]))
            while True:
                frame = _recv_frame(self._sock)
                if frame is None:
                    raise errors.InternalError(
                        "zprted: daemon vanished mid-job")
                [msg] = dss.unpack(frame)
                kind = msg[0]
                if kind == "job":
                    self.last_job_id = msg[1]
                elif kind == "io":
                    _, rank, label, line = msg
                    sink = stderr if label else stdout
                    if tag_output:
                        sink.write(f"[{rank}{label}] {line}")
                    else:
                        sink.write(line)
                    sink.flush()
                elif kind == "note":
                    stderr.write(msg[1])
                    stderr.flush()
                elif kind == "queued":
                    self.last_queue_position = int(msg[1])
                    stderr.write(
                        f"zprted: launch queued at position "
                        f"{self.last_queue_position}\n")
                    stderr.flush()
                elif kind == "exit":
                    return int(msg[1])
                elif kind == "err":
                    raise errors.InternalError(f"zprted launch: {msg[1]}")
        except OSError as e:
            raise errors.InternalError(
                f"zprted: daemon connection lost mid-job: {e}") from e

    def respawn(self, job_id: str, ranks: list[int],
                timeout: float = 30.0) -> list[int]:
        return self._call(["respawn", str(job_id),
                           [int(r) for r in ranks]], wait=timeout)

    def resize(self, job_id: str, n: int,
               timeout: float = 30.0) -> dict:
        """Elastic resize of a running ft job: grow spawns fresh ranks
        that FT_JOIN the live job, shrink retires the highest live
        ranks through the orderly-BYE path.  Returns the applied
        event (grown/retired ranks, event seq, store generation)."""
        return self._call(["resize", str(job_id), int(n)],
                          wait=timeout)

    def treeinfo(self) -> dict:
        """This daemon's tree coordinates: id, store address, depth,
        whether it is the root, and (at the root) the placement-order
        daemon list."""
        return self._call(["treeinfo"])

    def pids(self, job_id: str) -> dict[int, int]:
        return {int(r): int(p)
                for r, p in self._call(["pids", str(job_id)]).items()}

    def stat(self) -> dict:
        return self._call(["stat"])

    def metrics(self, job_id: str, rank: int | None = None,
                timeout: float = 10.0) -> dict:
        """Fleet-visible metrics: one rank's published snapshot, or the
        whole job's per-rank + aggregated view (staleness-stamped)."""
        req: list = ["metrics", str(job_id)]
        if rank is not None:
            req.append(int(rank))
        return self._call(req, wait=timeout)

    def ping(self) -> bool:
        return self._call(["ping"]) == "pong"

    def stop(self) -> bool:
        return bool(self._call(["stop"]))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def main(args: list[str] | None = None) -> int:
    """The ``zprted`` CLI: start a daemon, announce its ports, run until
    signalled or stopped by RPC."""
    ap = argparse.ArgumentParser(
        prog="zprted",
        description="Persistent runtime daemon (PRRTE/DVM analog): "
                    "hosts the PMIx store, launches zmpirun --dvm jobs, "
                    "watches children, floods fault events, respawns "
                    "ranks.",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="control (RPC) port; 0 = ephemeral")
    ap.add_argument("--pmix-port", type=int, default=0,
                    help="PMIx store port; 0 = ephemeral")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="bind the HTTP GET /metrics scrape endpoint "
                         "(Prometheus text exposition) on this port; "
                         "0 = ephemeral; off by default")
    ap.add_argument("--parent", default=None, metavar="HOST:PORT",
                    help="attach this daemon as a CHILD of an existing "
                         "zprted (its control port): store verbs route "
                         "up the tree, launch/fault/invalidation "
                         "traffic rides the persistent link — one "
                         "zprted per host, ranks talk to theirs")
    ns = ap.parse_args(args)
    dvm = Dvm(ns.host, ns.port, ns.pmix_port,
              metrics_port=ns.metrics_port, parent=ns.parent)
    extra = ""
    if dvm.metrics_http is not None:
        extra = (f" metrics={dvm.host}:"
                 f"{dvm.metrics_http.address[1]}")
    if ns.parent:
        extra += f" parent={ns.parent} depth={dvm.tree_depth}"
    print(f"zprted ready dvm={dvm.host}:{dvm.address[1]} "
          f"pmix={dvm.host}:{dvm.pmix.address[1]}{extra}", flush=True)

    def on_signal(signum, _frame):
        dvm.stop()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    dvm.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
