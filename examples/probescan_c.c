/* probescan_c.c — second round-4 C ABI acceptance program.
 *
 * Covers the calls subcomm_c.c does not: MPI_Probe/Iprobe (matching
 * introspection before the receive), MPI_Waitany/Testall, prefix scans
 * (MPI_Scan/MPI_Exscan), the v-variant collectives
 * (Gatherv/Scatterv/Allgatherv with ragged counts/displacements),
 * MPI_Reduce_scatter_block, user-defined reduction operators
 * (MPI_Op_create), MPI_Error_string and MPI_Type_get_extent.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "zompi_mpi.h"

#define CHECK(cond, msg)                                      \
  do {                                                        \
    if (!(cond)) {                                            \
      fprintf(stderr, "FAIL rank %d: %s\n", rank, msg);       \
      return 1;                                               \
    }                                                         \
  } while (0)

/* user op: modular sum (mod 1000) — exercises the Op_create path with
 * something the predefined table cannot express */
static void modsum(void *invec, void *inoutvec, int *len,
                   MPI_Datatype *dt) {
  long *a = (long *)invec, *b = (long *)inoutvec;
  (void)dt;
  for (int i = 0; i < *len; i++) b[i] = (a[i] + b[i]) % 1000;
}

int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);

  /* 1. Probe before receive: learn source/size without consuming */
  int next = (rank + 1) % size, prev = (rank + size - 1) % size;
  long payload[3] = {rank * 7L, rank * 7L + 1, rank * 7L + 2};
  CHECK(MPI_Send(payload, 3, MPI_LONG, next, 21, MPI_COMM_WORLD) ==
            MPI_SUCCESS, "send");
  MPI_Status st;
  CHECK(MPI_Probe(MPI_ANY_SOURCE, 21, MPI_COMM_WORLD, &st) ==
            MPI_SUCCESS, "Probe");
  CHECK(st.MPI_SOURCE == prev && st.MPI_TAG == 21, "Probe status");
  int pn = -1;
  MPI_Get_count(&st, MPI_LONG, &pn);
  CHECK(pn == 3, "Probe count");
  long got[3];
  CHECK(MPI_Recv(got, 3, MPI_LONG, st.MPI_SOURCE, 21, MPI_COMM_WORLD,
                 MPI_STATUS_IGNORE) == MPI_SUCCESS, "recv after probe");
  CHECK(got[0] == prev * 7L, "probe payload");

  /* Iprobe: nothing pending on tag 99 */
  int flag = -1;
  CHECK(MPI_Iprobe(MPI_ANY_SOURCE, 99, MPI_COMM_WORLD, &flag,
                   MPI_STATUS_IGNORE) == MPI_SUCCESS && flag == 0,
        "Iprobe empty");

  /* 2. Waitany over two Irecvs: complete in send order */
  MPI_Request rq[2];
  long a = -1, b = -1;
  CHECK(MPI_Irecv(&a, 1, MPI_LONG, prev, 31, MPI_COMM_WORLD, &rq[0]) ==
            MPI_SUCCESS, "Irecv a");
  CHECK(MPI_Irecv(&b, 1, MPI_LONG, prev, 32, MPI_COMM_WORLD, &rq[1]) ==
            MPI_SUCCESS, "Irecv b");
  long v32 = rank + 3200;
  CHECK(MPI_Send(&v32, 1, MPI_LONG, next, 32, MPI_COMM_WORLD) ==
            MPI_SUCCESS, "send 32");
  int idx = -1;
  CHECK(MPI_Waitany(2, rq, &idx, MPI_STATUS_IGNORE) == MPI_SUCCESS,
        "Waitany");
  /* a fast neighbor may already have delivered tag 31 too, so Waitany
   * may legally return either index — but whichever it returns must be
   * completed, nulled, and carry the right payload */
  CHECK((idx == 0 || idx == 1) && rq[idx] == MPI_REQUEST_NULL,
        "Waitany completion");
  CHECK(idx == 1 ? b == prev + 3200 : a == prev + 3100,
        "Waitany payload");
  long v31 = rank + 3100;
  CHECK(MPI_Send(&v31, 1, MPI_LONG, next, 31, MPI_COMM_WORLD) ==
            MPI_SUCCESS, "send 31");
  int all = 0;
  while (!all) {  /* Testall polls; completion arrives asynchronously */
    CHECK(MPI_Testall(2, rq, &all, MPI_STATUSES_IGNORE) == MPI_SUCCESS,
          "Testall");
  }
  CHECK(a == prev + 3100 && b == prev + 3200 &&
            rq[0] == MPI_REQUEST_NULL && rq[1] == MPI_REQUEST_NULL,
        "Testall completion");

  /* 3. Scan / Exscan */
  long mine = rank + 1, incl = -1, excl = -1;
  CHECK(MPI_Scan(&mine, &incl, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD) ==
            MPI_SUCCESS, "Scan");
  long want_incl = (long)(rank + 1) * (rank + 2) / 2;
  CHECK(incl == want_incl, "Scan value");
  CHECK(MPI_Exscan(&mine, &excl, 1, MPI_LONG, MPI_SUM,
                   MPI_COMM_WORLD) == MPI_SUCCESS, "Exscan");
  if (rank > 0)
    CHECK(excl == (long)rank * (rank + 1) / 2, "Exscan value");

  /* 4. ragged Gatherv/Scatterv/Allgatherv: rank r contributes r+1 */
  int *counts = malloc(sizeof(int) * size);
  int *displs = malloc(sizeof(int) * size);
  int total = 0;
  for (int r = 0; r < size; r++) {
    counts[r] = r + 1;
    displs[r] = total;
    total += r + 1;
  }
  long *ragged = malloc(sizeof(long) * (size + 1));
  for (int i = 0; i <= rank; i++) ragged[i] = rank * 100L + i;
  long *gat = malloc(sizeof(long) * total);
  memset(gat, 0xFF, sizeof(long) * total);
  CHECK(MPI_Gatherv(ragged, rank + 1, MPI_LONG, gat, counts, displs,
                    MPI_LONG, 0, MPI_COMM_WORLD) == MPI_SUCCESS,
        "Gatherv");
  if (rank == 0)
    for (int r = 0; r < size; r++)
      for (int i = 0; i <= r; i++)
        CHECK(gat[displs[r] + i] == r * 100L + i, "Gatherv value");
  /* scatter the assembled image back out */
  long *back = malloc(sizeof(long) * (size + 1));
  CHECK(MPI_Scatterv(gat, counts, displs, MPI_LONG, back, rank + 1,
                     MPI_LONG, 0, MPI_COMM_WORLD) == MPI_SUCCESS,
        "Scatterv");
  for (int i = 0; i <= rank; i++)
    CHECK(back[i] == rank * 100L + i, "Scatterv value");
  long *allg = malloc(sizeof(long) * total);
  CHECK(MPI_Allgatherv(ragged, rank + 1, MPI_LONG, allg, counts, displs,
                       MPI_LONG, MPI_COMM_WORLD) == MPI_SUCCESS,
        "Allgatherv");
  for (int r = 0; r < size; r++)
    for (int i = 0; i <= r; i++)
      CHECK(allg[displs[r] + i] == r * 100L + i, "Allgatherv value");

  /* 5. Reduce_scatter_block */
  long *vec = malloc(sizeof(long) * 2 * size);
  for (int i = 0; i < 2 * size; i++) vec[i] = rank + i;
  long piece[2] = {-1, -1};
  CHECK(MPI_Reduce_scatter_block(vec, piece, 2, MPI_LONG, MPI_SUM,
                                 MPI_COMM_WORLD) == MPI_SUCCESS,
        "Reduce_scatter_block");
  long ranksum = (long)size * (size - 1) / 2;
  for (int j = 0; j < 2; j++) {
    long want = ranksum + (long)size * (2 * rank + j);
    CHECK(piece[j] == want, "Reduce_scatter_block value");
  }

  /* 6. user-defined op through Allreduce and Reduce */
  MPI_Op mod;
  CHECK(MPI_Op_create(modsum, 1, &mod) == MPI_SUCCESS, "Op_create");
  long big = 700 + rank, m = -1;
  CHECK(MPI_Allreduce(&big, &m, 1, MPI_LONG, mod, MPI_COMM_WORLD) ==
            MPI_SUCCESS, "user-op allreduce");
  long want_mod = 0;
  for (int r = 0; r < size; r++) want_mod = (want_mod + 700 + r) % 1000;
  CHECK(m == want_mod, "user-op value");
  CHECK(MPI_Op_free(&mod) == MPI_SUCCESS && mod == MPI_OP_NULL,
        "Op_free");

  /* 7. diagnostics */
  char es[MPI_MAX_PROCESSOR_NAME];
  int el = -1;
  CHECK(MPI_Error_string(MPI_ERR_TRUNCATE, es, &el) == MPI_SUCCESS &&
            strstr(es, "TRUNCATE") && el > 0, "Error_string");
  MPI_Datatype col;
  MPI_Type_vector(3, 1, 4, MPI_DOUBLE, &col);
  long lb = -1, ext = -1;
  CHECK(MPI_Type_get_extent(col, &lb, &ext) == MPI_SUCCESS && lb == 0 &&
            ext == 9 * 8, "Type_get_extent");  /* (2*4+1) doubles */
  MPI_Type_commit(&col);
  MPI_Type_free(&col);

  MPI_Barrier(MPI_COMM_WORLD);
  printf("probescan_c rank %d/%d OK\n", rank, size);
  free(counts); free(displs); free(ragged); free(gat); free(back);
  free(allg); free(vec);
  MPI_Finalize();
  return 0;
}
