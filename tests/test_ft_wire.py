"""Fault tolerance over the wire plane: bookmark quiescence and
pessimistic logging/replay with per-process state over real sockets
(round-3 unweld — no shared matrix or log registry)."""

from test_tcp import run_tcp
from zhpe_ompi_tpu.ft.crcp import DistributedBookmarks
from zhpe_ompi_tpu.ft.vprotocol import ProcessLogger

N = 4


class TestWireBookmarks:
    def test_quiescent_after_drain(self):
        def prog(p):
            bk = DistributedBookmarks(p)
            ctx = bk.wrap()
            right, left = (p.rank + 1) % N, (p.rank - 1) % N
            ctx.send({"hop": p.rank}, dest=right, tag=1)
            got = ctx.recv(source=left, tag=1)
            assert got["hop"] == left
            return bk.quiescent()

        assert run_tcp(N, prog) == [True] * N

    def test_in_flight_detected(self):
        """An unreceived message must show as in flight on every rank's
        collective view, then clear once drained."""

        def prog(p):
            bk = DistributedBookmarks(p)
            ctx = bk.wrap()
            if p.rank == 0:
                ctx.send(b"pending", dest=1, tag=2)
            before = bk.in_flight()          # collective: 0->1 is 1
            pending = int(before[0, 1])
            if p.rank == 1:
                ctx.recv(source=0, tag=2)
            after_quiescent = bk.quiescent()  # collective: drained
            return (pending, after_quiescent)

        res = run_tcp(2, prog)
        assert res == [(1, True), (1, True)]


class TestWireLogging:
    def test_log_and_replay(self):
        """Each process logs its own rank's traffic; a replay context
        reproduces the received values deterministically."""

        def prog(p):
            logger = ProcessLogger(p)
            ctx = logger.wrap()
            right, left = (p.rank + 1) % N, (p.rank - 1) % N
            ctx.send(p.rank * 100, dest=right, tag=5)
            got = ctx.recv(source=left, tag=5)
            ctx.barrier()
            # simulate restart: replay this rank against its own log
            rp = logger.replay_context()
            rp.send(p.rank * 100, dest=right, tag=5)
            replayed = rp.recv(source=left, tag=5)
            return (got, replayed, rp.fully_replayed,
                    logger.event_counts())

        res = run_tcp(N, prog)
        for r in range(N):
            got, replayed, done, counts = res[r]
            assert got == replayed == ((r - 1) % N) * 100
            assert done and counts == (1, 1)
