"""MPI-IO for multi-process jobs — ompio over the wire plane.

``io/file.py`` is the single-controller OMPIO analog (one File object
sees every rank).  This module is the same surface for launcher-started
OS processes, where each rank holds only its own state and coordination
must be explicit — the deployment the reference's ompio actually runs in:

- **individual / explicit-offset IO**: each rank's view maps etype
  offsets to file byte offsets (``_View.byte_offsets``) and pwrites
  through fs/posix — no coordination needed.
- **shared file pointer**: the ``sharedfp/lockedfile`` component
  (``ompi/mca/sharedfp/lockedfile``): the pointer lives in a sidecar
  file next to the data file, and fetch-and-add runs under ``flock`` —
  correct across processes with no server rank.
- **collective IO** (``write_all``/``read_all``): every rank ships its
  (offsets, bytes) run list to an aggregator over the endpoint, which
  drives the SAME fcoll component (two-phase coalescing) the
  single-controller path uses — one aggregation strategy, two planes.

Collective calls are collective over the endpoint's whole group; the
sidecar is created at open and removed at close by rank 0.
"""

from __future__ import annotations

import fcntl
import os

import numpy as np

from ..core import errhandler, errors
from ..core import info as info_mod
from ..datatype import Datatype
from .file import (
    BYTE,
    MODE_APPEND,
    MODE_CREATE,
    MODE_DELETE_ON_CLOSE,
    MODE_RDONLY,
    MODE_RDWR,
    MODE_WRONLY,
    _os_flags,
    _View,
)
from . import fbtl as fbtl_mod
from . import fcoll as fcoll_mod
from . import fs as fs_mod


class _ReservedCtx:
    """Endpoint proxy carrying a privately RESERVED collective-sequence
    window.  Nonblocking collective IO runs its body (gather/alltoall/
    scatter/barrier) on a worker thread, so tags must be drawn at CALL
    time, in program order, exactly like coll/nbc.py's schedules — a
    body drawing from the live endpoint at execution time would race
    any other collective on the same endpoint.  The proxy owns its own
    ``_coll_seq`` (starting at the window reserved by the caller) and
    delegates everything else to the real endpoint."""

    #: seq numbers consumed by ONE collective-IO op on every rank,
    #: regardless of path (write: gather|alltoall; read adds the reply
    #: round) — uniform so all ranks' live counters advance identically
    WINDOW = 4

    def __init__(self, ep, start: int):
        object.__setattr__(self, "_ep", ep)
        object.__setattr__(self, "_coll_seq", start)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_ep"), name)

    def __setattr__(self, name, value):
        if name == "_coll_seq":
            object.__setattr__(self, name, value)
        else:
            setattr(object.__getattribute__(self, "_ep"), name, value)

    @classmethod
    def reserve(cls, ep) -> "_ReservedCtx":
        """Reserve the window on the caller thread (call time)."""
        start = getattr(ep, "_coll_seq", 0)
        ep._coll_seq = start + cls.WINDOW
        return cls(ep, start)

class SharedPointerFile:
    """sharedfp/lockedfile: the shared pointer as ASCII in a sidecar
    file, updated under an exclusive flock."""

    def __init__(self, path: str, create: bool, initial: int = 0):
        self.path = path
        if create:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                if os.fstat(fd).st_size == 0:
                    os.write(fd, f"{initial:020d}".encode())
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
                os.close(fd)

    def fetch_add(self, n: int) -> int:
        fd = os.open(self.path, os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            cur = int(os.pread(fd, 20, 0) or b"0")
            os.pwrite(fd, f"{cur + n:020d}".encode(), 0)
            return cur
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def set(self, value: int) -> None:
        fd = os.open(self.path, os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            os.pwrite(fd, f"{value:020d}".encode(), 0)
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def get(self) -> int:
        fd = os.open(self.path, os.O_RDONLY)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            return int(os.pread(fd, 20, 0) or b"0")
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class WireFile(errhandler.HasErrhandler):
    """One rank's handle on a collectively-opened file (MPI_File for
    launcher jobs).  `ep` is the rank's endpoint (TcpProc)."""

    _default_errhandler = errhandler.ERRORS_RETURN

    def __init__(self, ep, path: str, mode: int = MODE_RDONLY, info=None):
        self.ep = ep
        self.path = path
        self.mode = mode
        self.info = info_mod.coerce(info)
        self.name = f"wirefile:{path}"
        self._fs = fs_mod.select_fs()
        self._fbtl = fbtl_mod.select_fbtl()
        self._fcoll = fcoll_mod.select_fcoll()
        # rank 0 creates; the others open the existing file (CREATE/EXCL
        # are collective-open semantics, not per-rank O_CREAT races)
        from .file import MODE_EXCL

        if ep.rank == 0:
            self._fd = self._fs.open(path, _os_flags(mode))
            ep.barrier()
        else:
            ep.barrier()  # file exists (if CREATE) before others open
            self._fd = self._fs.open(
                path, _os_flags(mode & ~(MODE_CREATE | MODE_EXCL)))
        start = self._fs.size(self._fd) if mode & MODE_APPEND else 0
        self._view = _View(0, BYTE, BYTE)
        self._pointer = start
        self._shfp = SharedPointerFile(
            path + ".zshfp", create=(ep.rank == 0), initial=start
        )
        ep.barrier()  # sidecar initialized before any shared-pointer op
        self._closed = False

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        if hasattr(self, "_ifbtl"):
            self._ifbtl.close()  # no async transfer may outlive the fd
        self._fs.close(self._fd)
        self._closed = True
        self.ep.barrier()  # all IO complete before any teardown
        if self.ep.rank == 0:
            self._shfp.unlink()
            if self.mode & MODE_DELETE_ON_CLOSE:
                self._fs.delete(self.path)
        self.ep.barrier()

    def __enter__(self) -> "WireFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _check_open(self) -> None:
        if self._closed:
            raise errors.ArgError("file is closed")

    # -- view ------------------------------------------------------------

    def set_view(self, disp: int, etype: Datatype,
                 filetype: Datatype | None = None) -> None:
        """This rank's (disp, etype, filetype); collective by MPI contract
        (every rank calls, each with its own triple)."""
        self._check_open()
        self._view = _View(disp, etype, filetype or etype)
        self._pointer = 0
        self.ep.barrier()
        if self.ep.rank == 0:
            self._shfp.set(0)
        self.ep.barrier()

    def get_view(self) -> tuple[int, Datatype, Datatype]:
        v = self._view
        return v.disp, v.etype, v.filetype

    # -- byte helpers ----------------------------------------------------

    def _as_bytes(self, buf, count: int) -> np.ndarray:
        arr = np.ascontiguousarray(buf)
        data = arr.reshape(-1).view(np.uint8)
        need = count * self._view.etype.size
        if data.size < need:
            raise errors.TruncateError(
                f"buffer {data.size}B < {need}B ({count} etypes)"
            )
        return data[:need]

    def _full_count(self, buf) -> int:
        nbytes = np.ascontiguousarray(buf).nbytes
        esz = self._view.etype.size
        if esz and nbytes % esz:
            raise errors.TypeError_(
                f"buffer ({nbytes}B) is not a whole number of etypes"
            )
        return nbytes // esz if esz else 0

    # -- explicit offset / individual pointer ----------------------------

    def read_at(self, offset: int, count: int) -> np.ndarray:
        self._check_open()
        offs = self._view.byte_offsets(offset, count)
        raw = self._fcoll.read(self._fbtl, self._fd, [offs])[0]
        dt = getattr(self._view.etype, "np_dtype", None)
        return raw.view(dt) if dt is not None else raw

    def write_at(self, offset: int, buf, count: int | None = None) -> int:
        self._check_open()
        if count is None:
            count = self._full_count(buf)
        data = self._as_bytes(buf, count)
        offs = self._view.byte_offsets(offset, count)
        self._fcoll.write(self._fbtl, self._fd, [(offs, data)])
        return count

    def read(self, count: int) -> np.ndarray:
        off, self._pointer = self._pointer, self._pointer + count
        return self.read_at(off, count)

    def write(self, buf, count: int | None = None) -> int:
        if count is None:
            count = self._full_count(buf)
        off, self._pointer = self._pointer, self._pointer + count
        return self.write_at(off, buf, count)

    def seek(self, offset: int) -> None:
        self._pointer = offset

    def tell(self) -> int:
        return self._pointer

    # -- nonblocking (MPI_File_iread/iwrite[_at]) ------------------------
    # Same async fbtl as the in-process path (file_iwrite.c:38 over
    # fbtl_posix_ipwritev.c): IO retires on a worker thread; the caller
    # overlaps compute and completes through wait/test.

    def _async_fbtl(self):
        if not hasattr(self, "_ifbtl"):
            self._ifbtl = fbtl_mod.AsyncFbtl(self._fbtl)
        return self._ifbtl

    def iread_at(self, offset: int, count: int):
        from .file import iread_offsets

        self._check_open()
        return iread_offsets(self._async_fbtl(), self._fcoll, self._fbtl,
                             self._fd,
                             self._view.byte_offsets(offset, count),
                             getattr(self._view.etype, "np_dtype", None))

    def iwrite_at(self, offset: int, buf, count: int | None = None):
        from .file import iwrite_offsets

        self._check_open()
        if count is None:
            count = self._full_count(buf)
        return iwrite_offsets(self._async_fbtl(), self._fcoll, self._fbtl,
                              self._fd,
                              self._view.byte_offsets(offset, count),
                              self._as_bytes(buf, count), count)

    def iread(self, count: int):
        off, self._pointer = self._pointer, self._pointer + count
        return self.iread_at(off, count)

    # -- nonblocking collective IO (MPI_File_iwrite_all/iread_all) -------
    # The reference backs these with libnbc-scheduled collectives
    # (ompi/mca/io/ompio's *_all_begin/_end + iread_all); here the whole
    # collective body (aggregation exchange + fbtl transfers) retires on
    # the async worker while the caller computes — every rank of the
    # group must call it, exactly like the blocking form, and pointers
    # advance at call time per the MPI nonblocking contract.

    def iwrite_all(self, buf, count: int | None = None):
        from .file import _MappedRequest

        self._check_open()
        if count is None:
            count = self._full_count(buf)
        data = self._as_bytes(buf, count).copy()
        offs = self._view.byte_offsets(self._pointer, count)
        self._pointer += count
        ctx = _ReservedCtx.reserve(self.ep)  # tags drawn at CALL time
        inner = self._async_fbtl().submit(
            self._write_all_offsets, offs, data, ctx)
        return _MappedRequest(inner, lambda _: count)

    def iread_all(self, count: int):
        from .file import _MappedRequest

        self._check_open()
        offs = self._view.byte_offsets(self._pointer, count)
        self._pointer += count
        ctx = _ReservedCtx.reserve(self.ep)  # tags drawn at CALL time
        inner = self._async_fbtl().submit(self._read_all_offsets, offs,
                                          ctx)
        return _MappedRequest(inner, lambda raw: raw)

    def iwrite(self, buf, count: int | None = None):
        if count is None:
            count = self._full_count(buf)
        off, self._pointer = self._pointer, self._pointer + count
        return self.iwrite_at(off, buf, count)

    # -- shared pointer (sharedfp/lockedfile) ----------------------------

    def write_shared(self, buf, count: int | None = None) -> int:
        if count is None:
            count = self._full_count(buf)
        off = self._shfp.fetch_add(count)
        return self.write_at(off, buf, count)

    def read_shared(self, count: int) -> np.ndarray:
        off = self._shfp.fetch_add(count)
        return self.read_at(off, count)

    def seek_shared(self, offset: int) -> None:
        """Collective: every rank calls with the same offset."""
        self.ep.barrier()
        if self.ep.rank == 0:
            self._shfp.set(offset)
        self.ep.barrier()

    def tell_shared(self) -> int:
        return self._shfp.get()

    # -- collective IO: fcoll over the endpoint --------------------------
    #
    # Aggregator count = fcoll_wire_aggregators (default 1).  With 1,
    # runs ship to rank 0, which drives the selected fcoll component —
    # the classic two-phase shape.  With A > 1, this is the vulcan shape
    # (ompi/mca/fcoll/vulcan): stripes of fcoll_dynamic_stripe bytes are
    # owned round-robin by A aggregator ranks, every rank alltoalls each
    # stripe's runs to its owner, and the owners write their disjoint
    # stripe sets concurrently (one process each).

    def _num_aggregators(self) -> int:
        from ..mca import var as mca_var

        mca_var.register(
            "fcoll_wire_aggregators", 1,
            "Aggregator ranks for wire-plane collective IO (1 = two-phase "
            "single aggregator; >1 = vulcan stripe-round-robin)",
            type=int,
        )
        return max(1, min(int(mca_var.get("fcoll_wire_aggregators", 1)),
                          self.ep.size))

    def _stripe_owner(self, offs: np.ndarray, naggr: int) -> np.ndarray:
        from ..mca import var as mca_var

        stripe = int(mca_var.get("fcoll_dynamic_stripe", 4 * 1024 * 1024))
        return (offs // stripe) % naggr

    def write_all(self, buf, count: int | None = None) -> int:
        """Collective write at each rank's individual pointer."""
        self._check_open()
        if count is None:
            count = self._full_count(buf)
        data = self._as_bytes(buf, count).copy()
        offs = self._view.byte_offsets(self._pointer, count)
        self._pointer += count
        self._write_all_offsets(offs, data,
                                ctx=_ReservedCtx.reserve(self.ep))
        return count

    def _write_all_offsets(self, offs: np.ndarray, data: np.ndarray,
                           ctx=None) -> None:
        """The collective write body (offsets already resolved): the
        shared engine for write_all and iwrite_all.  ``ctx`` is the
        tag-drawing endpoint view (a _ReservedCtx when running on a
        worker); collectives go through the free functions so the
        reserved sequence window is honored."""
        from ..coll import host as hostc

        ctx = self.ep if ctx is None else ctx
        naggr = self._num_aggregators()
        if naggr == 1:
            gathered = hostc.gather(ctx, (offs, data), root=0)
            if self.ep.rank == 0:
                self._fcoll.write(self._fbtl, self._fd, gathered)
        else:
            owner = self._stripe_owner(offs, naggr)
            outbox = [
                (offs[owner == a], data[owner == a]) if a < naggr else None
                for a in range(self.ep.size)
            ]
            inbox = hostc.alltoall(ctx, outbox)
            if self.ep.rank < naggr:
                mine = [p for p in inbox if p is not None]
                self._fcoll.write(self._fbtl, self._fd, mine)
        # completion sync: a token allgather DRAWN FROM THE RESERVED
        # WINDOW — the endpoint's fixed-tag barrier (0x7FFD, no
        # sequence) would cross-match between overlapping nonblocking
        # collective bodies
        hostc.allgather(ctx, 0)

    def read_all(self, count: int) -> np.ndarray:
        """Collective read at each rank's individual pointer."""
        self._check_open()
        offs = self._view.byte_offsets(self._pointer, count)
        self._pointer += count
        return self._read_all_offsets(offs,
                                      ctx=_ReservedCtx.reserve(self.ep))

    def _read_all_offsets(self, offs: np.ndarray, ctx=None) -> np.ndarray:
        """The collective read body (offsets already resolved): the
        shared engine for read_all and iread_all; ``ctx`` as in
        :meth:`_write_all_offsets`."""
        from ..coll import host as hostc

        ctx = self.ep if ctx is None else ctx
        naggr = self._num_aggregators()
        if naggr == 1:
            all_offs = hostc.gather(ctx, offs, root=0)
            if self.ep.rank == 0:
                raws = self._fcoll.read(self._fbtl, self._fd, all_offs)
                raw = hostc.scatter(ctx, raws, root=0)
            else:
                raw = hostc.scatter(ctx, None, root=0)
        else:
            owner = self._stripe_owner(offs, naggr)
            outbox = [
                offs[owner == a] if a < naggr else None
                for a in range(self.ep.size)
            ]
            inbox = hostc.alltoall(ctx, outbox)
            if self.ep.rank < naggr:
                reqs = [o if o is not None else np.empty(0, np.int64)
                        for o in inbox]
                raws = self._fcoll.read(self._fbtl, self._fd, reqs)
            else:
                raws = [None] * self.ep.size
            back = hostc.alltoall(ctx, raws)
            raw = np.empty(offs.size, dtype=np.uint8)
            for a in range(naggr):
                routed = int((owner == a).sum())
                piece = back[a]
                got = 0 if piece is None else int(piece.size)
                if got != routed:
                    # A short or missing reply must never surface the
                    # uninitialized np.empty bytes as file data.
                    raise errors.TruncateError(
                        f"aggregator {a} returned {got} bytes for "
                        f"{routed} requested"
                    )
                if routed:
                    raw[owner == a] = piece
        dt = getattr(self._view.etype, "np_dtype", None)
        return raw.view(dt) if dt is not None else raw

    # -- size management -------------------------------------------------

    def get_size(self) -> int:
        self._check_open()
        return self._fs.size(self._fd)

    def set_size(self, size: int) -> None:
        """Collective."""
        self._check_open()
        self.ep.barrier()
        if self.ep.rank == 0:
            self._fs.resize(self._fd, size)
        self.ep.barrier()

    def sync(self) -> None:
        """MPI_File_sync: flush this rank then barrier (collective)."""
        self._check_open()
        self._fs.sync(self._fd)
        self.ep.barrier()
