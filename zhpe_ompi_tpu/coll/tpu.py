"""coll/tpu — XLA-native collectives over the ICI mesh.

The inversion of the reference's ``coll/cuda`` (SURVEY.md §2.4): where
``coll_cuda_allreduce.c:30-69`` stages device buffers to the host and
delegates to a CPU component, this component keeps data in HBM and lowers
every operation to the XLA collective the TPU executes natively on ICI —
``psum``/``pmax``/``pmin``, ``all_gather``, ``all_to_all``, ``psum_scatter``,
with ``axis_index_groups`` carrying split sub-communicators in one op.

Ops without a native XLA reduction (PROD, bitwise, MINLOC/MAXLOC, user ops)
fall back to the algorithmic layer's recursive doubling — the same shape the
reference uses when hardware collectives don't cover an op.  Logical ops are
re-expressed arithmetically (LAND = pmin(x≠0), LOR = pmax(x≠0),
LXOR = psum(x≠0) mod 2) so they still ride a single native collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import ops as _ops
from . import algorithms as alg
from .framework import CollComponent, CollModule


# -- device liveness probe (the killable-child half) ------------------------
#
# The tiny deadline-bounded psum the device-plane fault loop runs
# (parallel/mesh.py arms it through utils/deadline.run_probe, which
# prepends the internal-watchdog preamble): a wedged TPU participant
# surfaces as an indefinite XLA hang, so the probe must live where it
# can be killed — a subprocess — and die from the inside at its
# deadline even if the outer kill is delayed.  ``ZMPI_DEVICE_WEDGE=1``
# is the fault-injection hook (ft/inject.py's wedge_device exports it):
# the child wedges INSIDE the collective region, exactly where a real
# wedge holds the thread, so the whole classification ladder is
# drillable in CI without real hardware loss.

#: structured wedge-injection hook read by the probe child (and by the
#: armed guard's owning process — ft/inject.py documents the contract).
#: Value WEDGE_ALL wedges every probe child of the process (the
#: real-process drill); a rank number wedges only probes launched FOR
#: that rank (shared-process thread drills: the prober exports
#: PROBE_RANK_ENV, so a healthy survivor's probe never inherits the
#: victim's wedge).  The all-sentinel is deliberately NON-NUMERIC — a
#: rank-number value must never double as the process-wide switch
#: (wedging rank 1 must not wedge rank 0's probes)
WEDGE_ENV = "ZMPI_DEVICE_WEDGE"
WEDGE_ALL = "all"
PROBE_RANK_ENV = "ZMPI_PROBE_RANK"

PROBE_SRC = (
    "import json\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "p=os.environ.get('JAX_PLATFORMS')\n"
    "jax.config.update('jax_platforms', p) if p else None\n"
    "d=jax.devices()\n"
    f"_w=os.environ.get({WEDGE_ENV!r})\n"
    f"if _w is not None and _w in ({WEDGE_ALL!r}, os.environ.get("
    f"{PROBE_RANK_ENV!r}, '')):\n"
    "    time.sleep(3600)  # the injected wedge: hang mid-collective\n"
    "x=jnp.arange(float(len(d)))\n"
    "try:\n"
    "    s=jax.pmap(lambda v: jax.lax.psum(v,'i'),axis_name='i')(x)\n"
    "    total=float(jax.device_get(s)[0])\n"
    "except Exception:\n"
    "    # single-device/odd topology: a per-device round trip still\n"
    "    # proves the plane answers (the reduced claim, reported as is)\n"
    "    total=float(jax.device_get(jax.device_put(x[0],d[-1])))\n"
    "print(json.dumps({'n':len(d),'platform':d[0].platform,"
    "'psum':total}))\n"
)


def _groups(comm):
    return comm.index_groups


def _psum(comm, x):
    return lax.psum(x, comm.axis, axis_index_groups=_groups(comm))


def _pmax(comm, x):
    return lax.pmax(x, comm.axis, axis_index_groups=_groups(comm))


def _pmin(comm, x):
    return lax.pmin(x, comm.axis, axis_index_groups=_groups(comm))


def allreduce(comm, x, op):
    name = op.name
    if name == "MPI_SUM":
        return _psum(comm, x)
    if name == "MPI_MAX":
        return _pmax(comm, x)
    if name == "MPI_MIN":
        return _pmin(comm, x)
    if name == "MPI_LAND":
        return _pmin(comm, (x != 0).astype(jnp.int32)).astype(x.dtype)
    if name == "MPI_LOR":
        return _pmax(comm, (x != 0).astype(jnp.int32)).astype(x.dtype)
    if name == "MPI_LXOR":
        return (_psum(comm, (x != 0).astype(jnp.int32)) % 2).astype(x.dtype)
    # PROD / bitwise / MINLOC / MAXLOC / user ops: algorithmic path
    return alg.allreduce_recursive_doubling(comm, x, op)


def reduce(comm, x, op, root=0):
    # SPMD: computing the allreduce everywhere IS the fastest reduce on an
    # ICI mesh (result significant at root, per MPI semantics)
    return allreduce(comm, x, op)


def bcast(comm, x, root=0):
    # one native collective: zero every contribution but root's and all-reduce
    rank = comm.rank()
    contrib = jax.tree.map(
        lambda a: jnp.where(rank == root, a, jnp.zeros_like(a)), x
    )
    return jax.tree.map(lambda a: _psum(comm, a), contrib)


def barrier(comm, token=None):
    # alg._barrier_token ties the wire payload to the caller's token without
    # a foldable *0; _seal_token zeroes the psum result the same way
    return alg._seal_token(_psum(comm, alg._barrier_token(comm, token)))


def allgather(comm, x):
    x = alg._stack_shape(x)
    return lax.all_gather(
        x, comm.axis, axis_index_groups=_groups(comm), tiled=True
    )


def allgatherv(comm, x, counts):
    n = comm.size
    mx = max(counts)
    pad = mx - x.shape[0]
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    g = lax.all_gather(x, comm.axis, axis_index_groups=_groups(comm))
    parts = [g[i, : counts[i]] for i in range(n)]
    return jnp.concatenate(parts, axis=0)


def alltoall(comm, x):
    n = comm.size
    if x.shape[0] % n:
        from ..core import errors

        raise errors.CountError(
            f"alltoall needs dim0 divisible by comm size {n}"
        )
    return lax.all_to_all(
        x, comm.axis, split_axis=0, concat_axis=0,
        axis_index_groups=_groups(comm), tiled=True,
    )


def reduce_scatter(comm, x, op):
    if op.name == "MPI_SUM":
        return lax.psum_scatter(
            x, comm.axis, scatter_dimension=0,
            axis_index_groups=_groups(comm), tiled=True,
        )
    return alg.reduce_scatter_recursive_halving(comm, x, op)


def reduce_scatter_block(comm, x, op):
    # MPI_Reduce_scatter_block (equal counts) — the contract psum_scatter
    # implements natively
    return reduce_scatter(comm, x, op)


def alltoallv(comm, x, counts):
    """Padded alltoallv on the native all_to_all: x is (n, max_send, ...)
    blocks, counts the n x n static matrix; validation, padding and
    count-masking are shared with the algorithmic transport
    (alg.alltoallv_prepare — cf. coll_base_alltoallv.c:125)."""
    blocks, _ = alg.alltoallv_prepare(comm, x, counts)
    return lax.all_to_all(
        blocks, comm.axis, split_axis=0, concat_axis=0,
        axis_index_groups=_groups(comm), tiled=False,
    )


def scan(comm, x, op):
    return alg.scan_recursive_doubling(comm, x, op)


def exscan(comm, x, op):
    return alg.exscan_recursive_doubling(comm, x, op)


def gather(comm, x, root=0):
    return allgather(comm, x)


def scatter(comm, x, root=0):
    # take own block of root's buffer after a single-collective bcast
    n = comm.size
    full = bcast(comm, x, root)
    buf, _ = alg._chunked(full, n)
    return jnp.take(buf, comm.rank(), axis=0)


class TpuCollComponent(CollComponent):
    # Priority 40 < tuned's 50: the decision layer is the default entry point
    # (mirroring the reference, where tuned outranks basic/others) and its
    # "xla" algorithm delegates here for the cases where hardware collectives
    # win — which is most of them.  `--mca coll tpu` selects this component
    # directly, bypassing decisions.
    name = "tpu"
    default_priority = 40

    def available(self) -> bool:
        return True  # XLA collectives exist on every backend

    def comm_query(self, comm) -> CollModule:
        mod = CollModule(
            allreduce=allreduce,
            reduce=reduce,
            bcast=bcast,
            barrier=barrier,
            allgather=allgather,
            allgatherv=allgatherv,
            alltoall=alltoall,
            alltoallv=alltoallv,
            reduce_scatter=reduce_scatter,
            reduce_scatter_block=reduce_scatter_block,
            scan=scan,
            exscan=exscan,
            gather=gather,
            scatter=scatter,
        )
        if comm.uniform_size is None:
            # non-uniform partitions: only ops whose XLA form takes
            # axis_index_groups with unequal group sizes remain
            mod.scan = None
            mod.exscan = None
            mod.scatter = None
            mod.gather = None
            mod.allgather = None
            mod.allgatherv = None
            mod.alltoall = None
            mod.alltoallv = None
            mod.reduce_scatter = None
            mod.reduce_scatter_block = None
        return mod
