"""Collective checkpoint I/O — the OMPIO-analog two-phase plane.

``runtime/checkpoint.py`` is the serial half of the story: one writer
pickles one process's pytree.  This module is the COLLECTIVE half the
reference's io/fcoll/fbtl stack exists for, re-shaped for recovery time
as a first-class metric: every rank contributes its own shard of the
job state, the shards ride an fcoll-style two-phase exchange over the
han locality hierarchy, and a manifest of digests makes torn shards a
LOUD degradation instead of a silent unpickle.

The write path (``CollectiveCheckpointer.save``):

1. **snapshot** — the state pytree is flattened and copied to host NOW
   (the caller may overwrite its buffers immediately); each rank takes
   its byte-range shard of every leaf (near-equal chunks, so restore
   re-assembles exact full leaves in ``ZeroOptimizer.reshard``-
   compatible full-state form regardless of the restoring mesh's size).
2. **phase one (metadata exchange)** — one allgather carries every
   rank's per-leaf ``(nbytes, digest, skip)`` vector; offsets into the
   step's data file fall out as prefix sums every rank computes
   identically.  A shard whose digest matches the previous complete
   manifest's entry is SKIPPED (``ckpt_delta_skips``) — the manifest
   re-links the previous step's bytes instead of re-writing them (the
   incremental/delta checkpoint).
3. **phase two (shuffle + stream)** — non-aggregator ranks isend their
   shard bytes to their HOST's aggregator (the locality-group leader,
   ``pt2pt/groups.locality_groups``) on a dedicated ckpt cid: one send
   per shard to ONE destination, riding the sm rings — never the flat
   all-pairs O(n²) (``ckpt_gather_bytes`` is the wire-delta gate).
   The sends ride the deferred-contract isend engine, so ``save``
   returns while the exchange drains: training steps keep committing
   (``ckpt_async_overlapped``) between the ``ckpt_begin`` and
   ``ckpt_commit`` flightrec events.
4. **stream** — each aggregator's background writer coalesces its
   group's shards into maximal runs (the fcoll two-phase sort) and
   streams them through the fbtl backend under a
   ``utils/deadline.Watchdog``-bounded retry ladder
   (``ckpt_write_retries`` attempts, backoff, then a typed
   :class:`CheckpointWriteError` — a wedged write becomes a FAULT,
   never a hang), then sends a done token to global rank 0.
5. **commit** — rank 0 collects the done tokens, writes the treedef
   and the manifest (shard → rank/offset/digest), and publishes the
   manifest atomically (tmp + rename).  A crash ANYWHERE before the
   rename leaves a step directory with no complete manifest, which
   restore heals away — the newest COMPLETE step is always the
   rollback point.  Rank 0 then releases every other rank with a
   commit token, so no rank's drain (and hence no blocking ``save``
   or ``wait``) finishes before the manifest outcome is settled — a
   fast member must never ``heal()`` the step directory out from
   under a still-streaming aggregator.

The read path (``restore``): walk complete manifests newest-first;
verify EVERY shard digest (and the treedef's) before unpickling
anything; a torn/corrupt shard counts in ``ckpt_integrity_rejects``
and degrades LOUDLY to the previous complete step
(``ckpt_degraded_restores``) — never a raise mid-recovery, never a
silent acceptance.  Restore is local (shared-filesystem contract, the
same one MPI-IO assumes), so a 3-rank survivor mesh restores a 4-rank
job's state without the dead rank.

Fault-seam hooks: ``ft/inject.py`` arms per-rank checkpoint-seam
faults (kill an aggregator mid-exchange, kill a writer mid-stream,
wedge an fbtl write past its deadline) through
:func:`install_fault_hook`; the plane consults :func:`fault_point` at
each seam.  :func:`corrupt_shard` flips bytes on disk for the torn-
shard drills.

Hygiene is observable like every other plane's: writer threads
register (:func:`live_writer_threads` must be [] once owners joined),
checkpoint roots register so the conftest session gate can assert
zero orphaned shard temps (:func:`orphaned_shard_temps`) and zero
incomplete manifests (:func:`incomplete_manifests`) after every test.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import Any, Callable

import numpy as np

import jax

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..runtime import flightrec, spc, ztrace
from . import fbtl as fbtl_mod

_stream = mca_output.open_stream("ckptio")

mca_var.register(
    "ckpt_write_deadline_s", 30.0,
    "Seconds one fbtl checkpoint write may take before its deadline "
    "watchdog declares the attempt wedged and the retry ladder takes "
    "over (utils/deadline.Watchdog bounds every stream write)",
    type=float,
)
mca_var.register(
    "ckpt_write_retries", 3,
    "Wedged/failed checkpoint-write attempts retried (with backoff) "
    "before the writer surfaces a typed CheckpointWriteError — the "
    "wedge becomes a fault, never a hang",
    type=int,
)
mca_var.register(
    "ckpt_delta", 1,
    "Incremental checkpoints: skip shards whose digest matches the "
    "previous complete manifest's entry (the manifest re-links the "
    "prior step's bytes); 0 re-writes every shard every step",
    type=int,
)

#: dedicated ckpt cid window: above the han span (0x7900..0x79FF),
#: below the control/collective cids (COLL_CID at 0x7FF0+), within 16
#: bits so ShrunkEndpoint generation translation preserves it
CKPT_CID_BASE = 0x7A00
CKPT_CID_WINDOWS = 0xF0
#: the aggregator → rank-0 done-token channel
CKPT_LEADER_CID = CKPT_CID_BASE + 0xFF

_MAGIC = "ZMPICKPT1"
_MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"


class CheckpointWriteError(errors.InternalError):
    """A checkpoint stream write exhausted its deadline/retry budget —
    the typed surface of a wedged fbtl backend (counted in
    ``ckpt_write_deadline_failures``)."""


# -- hygiene registries (consumed by the conftest session gate) -------------

_lock = threading.Lock()
_WRITER_THREADS: list[threading.Thread] = []
_CKPT_ROOTS: set[str] = set()


def _register_writer(t: threading.Thread) -> None:
    with _lock:
        _WRITER_THREADS[:] = [x for x in _WRITER_THREADS if x.is_alive()]
        _WRITER_THREADS.append(t)


def live_writer_threads() -> list[str]:
    """Async checkpoint writer/aggregator threads still running — must
    be [] once every checkpointer's owner waited/closed (a survivor
    here is a leaked stream)."""
    with _lock:
        _WRITER_THREADS[:] = [x for x in _WRITER_THREADS if x.is_alive()]
        return [t.name for t in _WRITER_THREADS]


def register_root(path: str) -> None:
    with _lock:
        _CKPT_ROOTS.add(os.path.abspath(path))


def _roots() -> list[str]:
    with _lock:
        return [d for d in _CKPT_ROOTS if os.path.isdir(d)]


def orphaned_shard_temps() -> list[str]:
    """``*.tmp`` shard/manifest partials left in any registered
    checkpoint root — a healthy plane leaves none (the atomic-publish
    rename consumes the manifest tmp; killed writers' partials are
    healed away by the next restore)."""
    out = []
    for root in _roots():
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in filenames:
                if name.endswith(".tmp"):
                    out.append(os.path.join(dirpath, name))
    return sorted(out)


def incomplete_manifests() -> list[str]:
    """Step directories without a COMPLETE manifest in any registered
    root — a crashed writer leaves one, the next restore's heal removes
    it; one surviving a test means nobody drove recovery."""
    out = []
    for root in _roots():
        for name in sorted(os.listdir(root)):
            d = os.path.join(root, name)
            if not (name.startswith(_STEP_PREFIX) and os.path.isdir(d)):
                continue
            if _read_manifest(d) is None:
                out.append(d)
    return out


# -- fault-seam hooks (armed by ft/inject.py) --------------------------------

_FAULT_HOOKS: list[Callable] = []


def install_fault_hook(hook: Callable) -> Callable[[], None]:
    """Register a checkpoint-seam fault hook (``hook(seam, rank,
    **info)``); returns the remover.  Hooks fire synchronously at the
    seams — a hook raises/kills/sleeps to inject its fault."""
    with _lock:
        _FAULT_HOOKS.append(hook)

    def remove() -> None:
        with _lock:
            if hook in _FAULT_HOOKS:
                _FAULT_HOOKS.remove(hook)

    return remove


def fault_point(seam: str, rank: int, **info: Any) -> None:
    """One checkpoint seam: consult every armed hook (deterministic
    order).  Hot-path cheap: the common case is an empty list."""
    if not _FAULT_HOOKS:
        return
    with _lock:
        hooks = list(_FAULT_HOOKS)
    for hook in hooks:
        hook(seam, rank, **info)


# -- manifest helpers --------------------------------------------------------


def _digest(data) -> str:
    return hashlib.blake2b(bytes(data), digest_size=16).hexdigest()


def _read_manifest(step_dir: str) -> dict | None:
    """The step's manifest if it is COMPLETE, else None (missing,
    unparsable, foreign magic, or published without the completeness
    marker — all the same thing to restore: not a rollback point)."""
    path = os.path.join(step_dir, _MANIFEST)
    try:
        with open(path) as f:
            m = json.load(f)
    # zlint: disable=ZL004 -- classified degradation: an absent/torn manifest IS the incomplete-step signal; the caller skips the step (and the heal removes it), it never restores from one
    except (OSError, ValueError):
        return None
    if m.get("magic") != _MAGIC or not m.get("complete"):
        return None
    return m


def corrupt_shard(directory: str, step: int | None = None,
                  leaf: int = 0, rank: int = 0) -> str:
    """TEST SEAM: flip the bytes of one shard on disk (the torn-shard
    drill).  Returns the file corrupted.  Restore must detect it by
    digest, count it in ``ckpt_integrity_rejects`` and degrade to the
    previous complete step."""
    steps = _complete_steps(directory)
    if step is None:
        if not steps:
            raise errors.ArgError(f"no complete checkpoint in {directory}")
        step = steps[-1]
    m = _read_manifest(os.path.join(directory, f"{_STEP_PREFIX}{step}"))
    if m is None:
        raise errors.ArgError(f"no complete manifest for step {step}")
    for entry in m["shards"]:
        if entry["leaf"] == leaf and entry["rank"] == rank:
            if entry["nbytes"] == 0:
                raise errors.ArgError("cannot corrupt an empty shard")
            path = os.path.join(directory, entry["file"])
            with open(path, "r+b") as f:
                f.seek(entry["offset"])
                raw = f.read(entry["nbytes"])
                f.seek(entry["offset"])
                f.write(bytes(b ^ 0xFF for b in raw))
            return path
    raise errors.ArgError(f"no shard (leaf={leaf}, rank={rank}) in "
                          f"step {step}")


def _complete_steps(directory: str) -> list[int]:
    out = []
    for name in os.listdir(directory):
        if not name.startswith(_STEP_PREFIX):
            continue
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        if _read_manifest(os.path.join(directory, name)) is not None:
            out.append(step)
    return sorted(out)


# -- the deadline-bounded stream write ---------------------------------------


def _deadline_pwritev(base: fbtl_mod.FbtlComponent, fd: int, runs,
                      data: np.ndarray, rank: int) -> int:
    """One coalesced stream write, bounded: every attempt runs under a
    ``utils/deadline.Watchdog``; a wedged/raising attempt is retried
    with backoff (``ckpt_write_retries``) before surfacing the typed
    :class:`CheckpointWriteError`.  pwrite is idempotent at fixed
    offsets, so a late-but-landed attempt re-written by its retry is
    harmless."""
    from ..utils import deadline as deadline_mod

    deadline_s = float(mca_var.get("ckpt_write_deadline_s", 30.0))
    retries = int(mca_var.get("ckpt_write_retries", 3))
    last: BaseException | None = None
    for attempt in range(retries + 1):
        if attempt:
            spc.record("ckpt_write_retries")
            time.sleep(min(0.05 * (2 ** (attempt - 1)), 1.0))  # backoff
        done = threading.Event()
        outcome: dict[str, Any] = {}

        def attempt_write(done=done, outcome=outcome):
            try:
                fault_point("write", rank, attempt=attempt)
                outcome["n"] = base.pwritev(fd, list(runs), data)
            except BaseException as e:  # noqa: BLE001 - crosses threads
                outcome["err"] = e
            finally:
                done.set()

        t = threading.Thread(target=attempt_write, daemon=True,
                             name=f"zmpi-ckpt-write-r{rank}")
        _register_writer(t)
        expired = threading.Event()
        wd = deadline_mod.Watchdog(deadline_s, expired.set,
                                   name=f"ckpt-write-wd-r{rank}")
        wd.arm()
        t.start()
        try:
            while not done.is_set() and not expired.is_set():
                done.wait(0.05)
        finally:
            wd.disarm()
        if not done.is_set():
            # wedged past the deadline: abandon the attempt (the hung
            # syscall's thread drains on its own; pwrite idempotence
            # makes its eventual landing harmless) and retry
            last = CheckpointWriteError(
                f"checkpoint write wedged past {deadline_s:.1f}s "
                f"deadline (attempt {attempt + 1})")
            mca_output.verbose(1, _stream,
                               "rank %d: %s", rank, last)
            continue
        err = outcome.get("err")
        if err is None:
            return int(outcome["n"])
        if not isinstance(err, Exception):
            raise err  # a BaseException (injected kill) is the rank's
            # own death, not a retryable I/O outcome
        last = err
        mca_output.verbose(1, _stream,
                           "rank %d: checkpoint write attempt %d "
                           "failed: %r", rank, attempt + 1, err)
    spc.record("ckpt_write_deadline_failures")
    raise CheckpointWriteError(
        f"checkpoint write failed after {retries + 1} attempts: {last!r}")


# -- the collective checkpointer ---------------------------------------------


class CollectiveCheckpointer:
    """Sharded collective checkpoint/restore over a directory.

    Duck-type compatible with :class:`~zhpe_ompi_tpu.runtime.checkpoint.
    Checkpointer` (``save``/``wait``/``restore``/``all_steps``/
    ``latest_step``), so ``FtTrainLoop`` and ``ft/recovery.rollback``
    drive it unchanged — plus the collective surface: construct one per
    rank over a SHARED directory, :meth:`bind` the current live
    endpoint, and every rank's ``save(step, state)`` call is collective
    over it.  ``ep=None`` (or size 1) is the degenerate single-writer
    mode: same manifest/digest/delta/deadline machinery, no exchange —
    the thread-plane unit tests and single-rank jobs.
    """

    #: FtTrainLoop reads this to choose non-blocking saves (the
    #: snapshot-then-stream overlap)
    async_capable = True

    def __init__(self, directory: str, ep=None, keep: int = 3,
                 check_quiescent: bool = True,
                 drain_timeout: float = 60.0):
        self.directory = directory
        self.ep = ep
        self.keep = keep
        self.check_quiescent = check_quiescent
        self.drain_timeout = float(drain_timeout)
        os.makedirs(directory, exist_ok=True)
        register_root(directory)
        self._worker: threading.Thread | None = None
        self._error: BaseException | None = None
        # save/wait/restore serialize under one reentrant lock, the
        # runtime/checkpoint.py discipline: concurrent survivor
        # rollbacks must not double-join the worker or race the heal
        self._op_lock = threading.RLock()
        #: per-save statistics of the LAST completed local save (tests
        #: and benchmarks read them; cross-rank truth is the counters)
        self.last_stats: dict[str, Any] = {}

    # -- topology ----------------------------------------------------------

    def bind(self, ep) -> None:
        """Adopt the current live endpoint (FtTrainLoop re-binds after
        every recovery: the survivor mesh is a fresh endpoint).  The
        ckpt cids alias to the logical collective cid, so a recovery's
        ``revoke(COLL_CID)`` unblocks gather recvs parked on a dead
        peer exactly like the flat collectives'."""
        self.ep = ep
        if ep is None:
            return
        state = getattr(ep, "ft_state", None)
        if state is not None and hasattr(state, "alias_cid"):
            from ..coll.host import COLL_CID

            for w in range(CKPT_CID_WINDOWS):
                state.alias_cid(CKPT_CID_BASE + w, COLL_CID)
            state.alias_cid(CKPT_LEADER_CID, COLL_CID)

    def _topology(self):
        """(rank, size) of the bound endpoint — (0, 1) when absent or
        singleton (everyone their own aggregator, no exchange)."""
        ep = self.ep
        if ep is None or getattr(ep, "size", 1) <= 1:
            return 0, 1
        return ep.rank, ep.size

    def _my_boot_token(self, rank: int):
        """This rank's OWN locality identity, contributed into the
        phase-one metadata exchange."""
        if self.ep is None:
            return None
        from ..pt2pt import groups as groups_mod

        return groups_mod.boot_token_of(self.ep, rank)

    @staticmethod
    def _consensus_groups(meta_all):
        """The han host-group map derived from the EXCHANGED locality
        tokens, identically on every rank.  Local ``locality_groups``
        views legitimately diverge after a recovery (a rejoiner is a
        singleton to peers whose modex card for it is stale, and sees
        stale cards itself) — a split-brain group map deadlocks the
        done-token/commit-release protocol, so the aggregator election
        must ride the same collective the shard metadata does.  A rank
        with no provable locality (token None) is its own singleton
        group, exactly as in ``pt2pt.groups.locality_groups``."""
        tok_by_rank = {int(e["rank"]): e.get("loc") for e in meta_all}
        by_token: dict[str, list[int]] = {}
        groups: list[list[int]] = []
        for r in sorted(tok_by_rank):
            tok = tok_by_rank[r]
            if tok is None:
                groups.append([r])
                continue
            members = by_token.get(tok)
            if members is None:
                members = by_token[tok] = [r]
                groups.append(members)
            else:
                members.append(r)
        groups.sort(key=lambda g: g[0])
        return groups or [[0]]

    # -- save --------------------------------------------------------------

    def save(self, step: int, state, blocking: bool = False) -> None:
        """Collective sharded snapshot of ``state`` at ``step``.
        Snapshot (host copy + metadata exchange + shard isends) happens
        NOW; the stream (aggregation, fbtl writes, manifest commit)
        drains in the background unless ``blocking`` — the
        snapshot-then-stream overlap."""
        from ..runtime import checkpoint as ckpt_mod

        if self.check_quiescent:
            ckpt_mod.quiesce_check()
        with self._op_lock:
            # zlint: disable=ZL002 -- the checkpoint.py PR 2 contract: save/wait/restore serialize under ONE RLock; the writer thread never takes it
            self.wait()  # one outstanding checkpoint at a time
            step = int(step)
            leaves, treedef = jax.tree_util.tree_flatten(state)
            host_leaves = [np.asarray(np.array(leaf)) for leaf in leaves]
            rank, size = self._topology()
            # crash-epoch watermark for the commit-release wait: any
            # crash learned AFTER this point means the release token
            # may never arrive (its sender, or the commit it reports
            # on, is gone) — the drain abandons with a typed peer
            # fault instead of riding out drain_timeout.  Cumulative
            # epoch, not the failed set: a respawned rank 0 clears
            # its failed status long before a parked release recv
            # would otherwise notice.
            st = getattr(self.ep, "ft_state", None)
            epoch0 = st.crash_epoch() if st is not None else 0
            flightrec.record(flightrec.CKPT_BEGIN, step=step, rank=rank)
            sp = ztrace.begin(ztrace.CKPT, rank, step=step) \
                if ztrace.active else None

            # my byte-range shard of every leaf + phase-one metadata
            delta_on = bool(int(mca_var.get("ckpt_delta", 1)))
            prev = self._prev_manifest() if delta_on else None
            shards, meta = self._my_shards(host_leaves, prev, rank, size)
            gen = self._next_gen(step) if rank == 0 else 0
            entry = {"rank": rank, "gen": gen, "shards": meta,
                     "loc": self._my_boot_token(rank) if size > 1
                     else None}
            if size > 1:
                from ..coll import host as host_coll

                meta_all = host_coll.allgather(self.ep, entry)
            else:
                meta_all = [entry]
            plan = self._offsets(meta_all, step)
            # aggregator election by CONSENSUS, from the same exchange
            # the plan rides — never from the local locality view,
            # which diverges across a recovery (see _consensus_groups)
            groups = self._consensus_groups(meta_all)
            gi = next(i for i, g in enumerate(groups) if rank in g)
            agg = groups[gi][0]
            mca_output.verbose(
                1, _stream,
                "save step %d: rank=%d size=%d agg=%d groups=%s",
                step, rank, size, agg, groups)

            # phase two: non-aggregators isend their live shards to
            # the host aggregator (one destination, deferred engine)
            reqs = []
            if rank != agg:
                cid = CKPT_CID_BASE + (gi % CKPT_CID_WINDOWS)
                for li, data in shards.items():
                    if plan[(li, rank)].get("skip"):
                        continue
                    fault_point("gather", rank, leaf=li, step=step)
                    spc.record("ckpt_gather_bytes", int(data.size))
                    reqs.append(self.ep.isend(
                        data, agg, tag=step * 1024 + li, cid=cid))
            self.last_stats = {
                "step": step, "rank": rank, "aggregator": agg,
                "gather_sends": len(reqs),
                "gather_dests": {agg} if reqs else set(),
                "delta_skips": sum(
                    1 for m in meta if m.get("skip")),
            }

            def drain():
                try:
                    self._drain(step, plan, meta_all, groups, gi, agg,
                                rank, size, shards, reqs, treedef, sp,
                                epoch0)
                except BaseException as e:  # noqa: BLE001 - see wait()
                    self._error = e

            if blocking:
                drain()
                self._raise_pending()
            else:
                self._worker = threading.Thread(
                    target=drain, daemon=True,
                    name=f"zmpi-ckpt-writer-r{rank}")
                _register_writer(self._worker)
                self._worker.start()

    def _my_shards(self, host_leaves, prev, rank: int, size: int):
        """This rank's byte-range chunk of every leaf, plus its
        phase-one metadata vector (nbytes/digest/skip — the skip
        decision compares against the previous complete manifest's
        matching entry: the delta checkpoint)."""
        prev_entries = {}
        if prev is not None and int(prev.get("world", -1)) == size:
            for e in prev["shards"]:
                prev_entries[(e["leaf"], e["rank"])] = e
        shards: dict[int, np.ndarray] = {}
        meta = []
        for li, leaf in enumerate(host_leaves):
            raw = np.frombuffer(leaf.tobytes(), dtype=np.uint8)
            lo = raw.size * rank // size
            hi = raw.size * (rank + 1) // size
            chunk = raw[lo:hi]
            dig = _digest(chunk.tobytes())
            old = prev_entries.get((li, rank))
            skip = bool(old is not None and old["digest"] == dig
                        and old["nbytes"] == chunk.size)
            if skip:
                spc.record("ckpt_delta_skips")
            else:
                shards[li] = chunk
            meta.append({
                "leaf": li, "nbytes": int(chunk.size), "digest": dig,
                "skip": skip,
                "ref": ({"file": old["file"], "offset": old["offset"]}
                        if skip else None),
                "dtype": str(leaf.dtype), "shape": list(leaf.shape),
                "leaf_off": int(lo),
            })
        return shards, meta

    def _prev_manifest(self) -> dict | None:
        steps = _complete_steps(self.directory)
        if not steps:
            return None
        return _read_manifest(
            os.path.join(self.directory, f"{_STEP_PREFIX}{steps[-1]}"))

    def _next_gen(self, step: int) -> int:
        """Data-file generation for a re-checkpointed step: the old
        manifest keeps referencing ``data.<g>.bin`` while the new
        writer streams into ``data.<g+1>.bin``, so the atomic manifest
        rename is the ONLY commit point (a crash mid-rewrite degrades
        to the old complete version, never to torn bytes)."""
        m = _read_manifest(
            os.path.join(self.directory, f"{_STEP_PREFIX}{step}"))
        return int(m.get("gen", 0)) + 1 if m is not None else 0

    def _offsets(self, meta_all, step: int) -> dict:
        """The deterministic (leaf, rank) → placement plan every rank
        derives identically from the phase-one exchange: live shards
        pack densely into this step's data file (prefix sums in
        (leaf, rank) order), skipped shards carry their previous-step
        reference."""
        gen = int(meta_all[0].get("gen", 0))
        data_file = f"{_STEP_PREFIX}{step}/data.{gen}.bin"
        plan: dict = {"__gen__": gen, "__file__": data_file,
                      "__n_leaves__": len(meta_all[0]["shards"])}
        off = 0
        by_rank = {int(e["rank"]): e for e in meta_all}
        n_leaves = len(meta_all[0]["shards"])
        for li in range(n_leaves):
            for r in sorted(by_rank):
                m = by_rank[r]["shards"][li]
                if m["skip"]:
                    plan[(li, r)] = {"skip": True, "ref": m["ref"],
                                     "meta": m}
                else:
                    plan[(li, r)] = {"skip": False, "offset": off,
                                     "file": data_file, "meta": m}
                    off += int(m["nbytes"])
        return plan

    def _drain(self, step, plan, meta_all, groups, gi, agg, rank, size,
               shards, reqs, treedef, sp, epoch0=0) -> None:
        """The background half: complete the gather sends
        (non-aggregators), or receive + coalesce + stream the group's
        shards and token rank 0 (aggregators), or additionally collect
        the tokens and commit the manifest (rank 0)."""
        wrote = 0
        try:
            for r in reqs:
                r.wait(self.drain_timeout)
            if rank == agg:
                wrote = self._aggregate(step, plan, gi, groups[gi], rank,
                                        shards)
                if size > 1 and rank != 0:
                    cid = CKPT_LEADER_CID
                    self.ep.isend({"step": step, "agg": rank,
                                   "shards": wrote}, 0, tag=step,
                                  cid=cid).wait(self.drain_timeout)
            if rank == 0:
                others = [g[0] for g in groups if g[0] != 0]
                for a in others:
                    self.ep.recv(source=a, tag=step, cid=CKPT_LEADER_CID,
                                 timeout=self.drain_timeout)
                self._commit(step, plan, meta_all, size, treedef)
            elif size > 1:
                self._await_release(step, epoch0)
        finally:
            # commit release: no rank's drain may finish before the
            # manifest outcome is settled — a fast member returning
            # early would heal() the step directory out from under
            # aggregators still streaming into it.  Sent on EVERY exit
            # path of rank 0's drain (a dead member aborting the gather
            # or a dead aggregator aborting the commit included), so
            # survivors unblock promptly instead of riding out
            # drain_timeout and wedging the recovery agreement; a send
            # to a rank that itself died is not our fault to report
            # (recovery owns peer faults).
            if rank == 0:
                for r in range(1, size):
                    try:
                        self.ep.isend(
                            {"step": step, "released": True}, r, tag=step,
                            cid=CKPT_LEADER_CID).wait(self.drain_timeout)
                        mca_output.verbose(
                            1, _stream,
                            "step %d release sent to rank %d", step, r)
                    except errors.MpiError as e:
                        mca_output.verbose(
                            1, _stream,
                            "step %d commit release to rank %d dropped:"
                            " %r", step, r, e)
        if sp is not None:
            sp.end(step=step, shards=wrote)
        self.last_stats["shards_written"] = wrote

    def _await_release(self, step: int, epoch0: int) -> None:
        """Wait for rank 0's commit-release token, crash-aware:
        short-poll recvs so a releaser that died (or a crash that
        aborted the commit the token would report on) surfaces as a
        typed peer fault within one poll period instead of a
        drain_timeout stall.  The cumulative crash epoch is the
        abandon signal, NOT the failed set — a respawned rank 0
        clears its failed status long before a parked release recv
        would ever observe it."""
        st = getattr(self.ep, "ft_state", None)
        poll_s = min(0.25, self.drain_timeout)
        deadline = time.monotonic() + self.drain_timeout
        mca_output.verbose(1, _stream,
                           "awaiting step %d release (epoch0=%d)",
                           step, epoch0)
        while True:
            try:
                self.ep.recv(source=0, tag=step, cid=CKPT_LEADER_CID,
                             timeout=poll_s)
                mca_output.verbose(1, _stream, "step %d released", step)
                return
            except errors.ProcFailed:
                raise
            except errors.MpiError:
                if st is not None and st.crash_epoch() > epoch0:
                    raise errors.ProcFailed(
                        f"checkpoint step {step} commit release "
                        "abandoned: a peer crashed during the drain",
                        failed_ranks=st.failed(),
                    ) from None
                if time.monotonic() >= deadline:
                    raise

    def _aggregate(self, step, plan, gi, members, rank, shards) -> int:
        """One aggregator's stream: collect the group's live shards
        (own chunks directly, members' over the ckpt window), sort and
        coalesce into maximal contiguous runs (the fcoll two-phase
        pass over byte extents), and stream through the
        deadline-bounded fbtl write."""
        cid = CKPT_CID_BASE + (gi % CKPT_CID_WINDOWS)
        n_leaves = plan["__n_leaves__"]
        pieces: list[tuple[int, np.ndarray]] = []
        got = 0
        for li, data in shards.items():
            pieces.append((plan[(li, rank)]["offset"], data))
            got += 1
            fault_point("aggregate", rank, idx=got, leaf=li, src=rank,
                        step=step)
        for r in members:
            if r == rank:
                continue
            for li in range(n_leaves):
                ent = plan.get((li, r))
                if ent is None or ent.get("skip"):
                    continue
                data = self.ep.recv(source=r, tag=step * 1024 + li,
                                    cid=cid, timeout=self.drain_timeout)
                pieces.append(
                    (ent["offset"],
                     np.ascontiguousarray(data).view(np.uint8)))
                got += 1
                fault_point("aggregate", rank, idx=got, leaf=li, src=r,
                            step=step)
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step}")
        os.makedirs(step_dir, exist_ok=True)
        if not pieces:
            return 0
        # the fcoll two-phase coalesce: sort by file offset, merge
        # adjacent extents into maximal runs, one gathered stream write
        pieces.sort(key=lambda p: p[0])
        data = np.concatenate([p[1] for p in pieces]) \
            if len(pieces) > 1 else pieces[0][1]
        runs: list[tuple[int, int]] = []
        for off, buf in pieces:
            if runs and runs[-1][0] + runs[-1][1] == off:
                runs[-1] = (runs[-1][0], runs[-1][1] + int(buf.size))
            else:
                runs.append((off, int(buf.size)))
        base = fbtl_mod.select_fbtl()
        path = os.path.join(self.directory, plan["__file__"])
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            wrote = _deadline_pwritev(base, fd, runs, data, rank)
        finally:
            os.close(fd)
        spc.record("ckpt_shards_written", got)
        spc.record("ckpt_bytes_written", wrote)
        return got

    def _commit(self, step, plan, meta_all, size, treedef) -> None:
        """Rank 0's commit: treedef alongside the data, then the
        manifest published by tmp + atomic rename — the ONLY point a
        step becomes a rollback candidate."""
        import pickle

        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step}")
        os.makedirs(step_dir, exist_ok=True)
        gen = plan["__gen__"]
        td_raw = pickle.dumps(treedef)
        td_rel = f"{_STEP_PREFIX}{step}/treedef.{gen}.pkl"
        with open(os.path.join(self.directory, td_rel), "wb") as f:
            f.write(td_raw)
        by_rank = {int(e["rank"]): e for e in meta_all}
        entries = []
        total = 0
        for li in range(plan["__n_leaves__"]):
            for r in sorted(by_rank):
                ent = plan[(li, r)]
                m = ent["meta"]
                if ent.get("skip"):
                    file, off = ent["ref"]["file"], ent["ref"]["offset"]
                else:
                    file, off = ent["file"], ent["offset"]
                    total += int(m["nbytes"])
                entries.append({
                    "leaf": li, "rank": r, "file": file,
                    "offset": int(off), "nbytes": int(m["nbytes"]),
                    "digest": m["digest"],
                })
        manifest = {
            "magic": _MAGIC, "step": step, "gen": gen, "world": size,
            "n_leaves": plan["__n_leaves__"],
            "leaves": [{"dtype": m["dtype"], "shape": m["shape"]}
                       for m in meta_all[0]["shards"]],
            "treedef": {"file": td_rel, "digest": _digest(td_raw),
                        "nbytes": len(td_raw)},
            "shards": entries,
            "complete": True,
        }
        fault_point("manifest", 0, step=step)
        tmp = os.path.join(step_dir, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(step_dir, _MANIFEST))
        flightrec.record(flightrec.CKPT_COMMIT, step=step, rank=0,
                         bytes=total, shards=len(entries))
        self._retain()

    # -- wait/err ----------------------------------------------------------

    @property
    def in_flight(self) -> bool:
        """A previous save's stream is still draining (the overlap
        FtTrainLoop counts steps against)."""
        w = self._worker
        return w is not None and w.is_alive()

    def wait(self) -> None:
        with self._op_lock:
            self._join_worker()
            self._raise_pending()

    def _join_worker(self) -> None:
        with self._op_lock:
            if self._worker is not None:
                # zlint: disable=ZL002 -- the writer thread never takes _op_lock; holding it here is what keeps concurrent restores from double-joining (checkpoint.py PR 2 contract)
                self._worker.join(self.drain_timeout)
                alive = self._worker.is_alive()
                self._worker = None
                if alive:
                    raise CheckpointWriteError(
                        f"checkpoint stream did not drain within "
                        f"{self.drain_timeout}s")

    def _raise_pending(self) -> None:
        if self._error is None:
            return
        e, self._error = self._error, None
        if not isinstance(e, Exception):
            raise e  # the rank's own injected death (BaseException)
        if isinstance(e, (errors.ProcFailed, errors.Revoked)):
            # a peer died mid-exchange: the recovery pipeline owns that
            # fault (the step simply never committed — restore degrades
            # to the newest complete one); re-raising it here would
            # poison the post-recovery save with a stale corpse
            mca_output.verbose(
                1, _stream,
                "dropping stale in-stream peer failure: %r", e)
            return
        if isinstance(e, errors.MpiError):
            raise e
        raise errors.InternalError(f"checkpoint stream failed: {e!r}")

    # -- restore -----------------------------------------------------------

    def all_steps(self) -> list[int]:
        """Steps with a COMPLETE manifest, ascending."""
        return _complete_steps(self.directory)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def heal(self) -> list[str]:
        """Remove step directories a crashed writer left without a
        complete manifest (they can never restore) and stray manifest
        temps.  Returns what was removed."""
        removed = []
        with self._op_lock:
            for name in sorted(os.listdir(self.directory)):
                d = os.path.join(self.directory, name)
                if not (name.startswith(_STEP_PREFIX)
                        and os.path.isdir(d)):
                    continue
                if _read_manifest(d) is None:
                    shutil.rmtree(d, ignore_errors=True)
                    removed.append(d)
                    mca_output.verbose(
                        1, _stream,
                        "healed incomplete checkpoint %s", d)
                else:
                    tmp = os.path.join(d, _MANIFEST + ".tmp")
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                        removed.append(tmp)
        return removed

    def restore(self, step: int | None = None, shardings=None):
        """Digest-verified restore: newest COMPLETE step (or ``step``),
        every shard verified against its manifest digest BEFORE the
        treedef unpickles.  A torn/corrupt shard disqualifies its step
        LOUDLY (``ckpt_integrity_rejects``) and the walk degrades to
        the previous complete step (``ckpt_degraded_restores``) — a
        recovery never dies on a bad checkpoint while an older good one
        exists."""
        with self._op_lock:
            self._join_worker()
            self.heal()
            candidates = self.all_steps()
            if step is not None:
                candidates = [s for s in candidates if s == int(step)]
            if not candidates:
                raise errors.ArgError(
                    f"no complete checkpoint found in {self.directory}"
                    + (f" for step {step}" if step is not None else ""))
            degraded = 0
            for s in reversed(candidates):
                out = self._try_restore(s, shardings)
                if out is not None:
                    if degraded:
                        spc.record("ckpt_degraded_restores")
                    return out
                degraded += 1
                mca_output.verbose(
                    0, _stream,
                    "checkpoint step %d REJECTED by integrity "
                    "verification; degrading to the previous "
                    "complete step", s)
            raise errors.ArgError(
                f"every complete checkpoint in {self.directory} failed "
                f"integrity verification ({degraded} rejected)")

    def _try_restore(self, step: int, shardings):
        """One candidate: verify + assemble, or None (rejected)."""
        d = os.path.join(self.directory, f"{_STEP_PREFIX}{step}")
        m = _read_manifest(d)
        if m is None:
            return None
        base = fbtl_mod.select_fbtl()
        # every shard's bytes, digest-verified BEFORE any unpickle
        leaf_bytes: dict[int, dict[int, bytes]] = {}
        ok = True
        for entry in m["shards"]:
            path = os.path.join(self.directory, entry["file"])
            nbytes = int(entry["nbytes"])
            if nbytes == 0:
                raw = b""
            else:
                try:
                    fd = os.open(path, os.O_RDONLY)
                except OSError:
                    spc.record("ckpt_integrity_rejects")
                    ok = False
                    continue
                try:
                    raw = base.preadv(
                        fd, [(int(entry["offset"]), nbytes)], nbytes
                    ).tobytes()
                finally:
                    os.close(fd)
            spc.record("ckpt_restore_bytes", nbytes)
            if _digest(raw) != entry["digest"]:
                spc.record("ckpt_integrity_rejects")
                mca_output.verbose(
                    0, _stream,
                    "TORN SHARD (leaf=%d rank=%d step=%d): digest "
                    "mismatch against the manifest", entry["leaf"],
                    entry["rank"], step)
                ok = False
                continue
            leaf_bytes.setdefault(int(entry["leaf"]), {})[
                int(entry["rank"])] = raw
        td = m["treedef"]
        td_path = os.path.join(self.directory, td["file"])
        try:
            with open(td_path, "rb") as f:
                td_raw = f.read()
        except OSError:
            td_raw = b""
        if _digest(td_raw) != td["digest"]:
            spc.record("ckpt_integrity_rejects")
            ok = False
        if not ok:
            return None
        import pickle  # only after every digest verified

        treedef = pickle.loads(td_raw)
        leaves = []
        shard_leaves = (
            jax.tree_util.tree_flatten(
                shardings, is_leaf=lambda x: x is None)[0]
            if shardings is not None
            else [None] * int(m["n_leaves"]))
        for li, lm in enumerate(m["leaves"]):
            parts = leaf_bytes.get(li, {})
            raw = b"".join(parts[r] for r in sorted(parts))
            arr = np.frombuffer(raw, dtype=np.dtype(lm["dtype"])) \
                .reshape(tuple(lm["shape"])).copy()
            sh = shard_leaves[li]
            if sh is None:
                leaves.append(arr)
            else:
                leaves.append(jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, _a=arr: _a[idx]))
        return jax.tree_util.tree_unflatten(treedef, leaves), step

    # -- retention ---------------------------------------------------------

    def _retain(self) -> None:
        """Keep the last ``keep`` complete steps PLUS any older step a
        retained manifest still delta-references (deleting a referenced
        data file would tear every incremental descendant)."""
        steps = self.all_steps()
        if self.keep <= 0:
            return
        kept = set(steps[-self.keep:])
        referenced: set[int] = set()
        for s in kept:
            m = _read_manifest(
                os.path.join(self.directory, f"{_STEP_PREFIX}{s}"))
            if m is None:
                continue
            for entry in m["shards"]:
                top = entry["file"].split("/", 1)[0]
                if top.startswith(_STEP_PREFIX):
                    try:
                        referenced.add(int(top[len(_STEP_PREFIX):]))
                    except ValueError:
                        continue
        for s in steps:
            if s not in kept and s not in referenced:
                shutil.rmtree(
                    os.path.join(self.directory, f"{_STEP_PREFIX}{s}"),
                    ignore_errors=True)
