/* smsoak — mixed concurrent traffic over the sm rings: nonblocking
 * collectives + random-size pt2pt (eager AND rendezvous) + RMA,
 * interleaved across iterations. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"

int main(int argc, char **argv) {
  MPI_Init(&argc, &argv);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  int iters = argc > 1 ? atoi(argv[1]) : 100;
  long long cell = 0;
  MPI_Win win;
  MPI_Win_create(&cell, sizeof cell, sizeof cell, MPI_INFO_NULL,
                 MPI_COMM_WORLD, &win);
  size_t big_n = 300000;  /* 2.4 MB doubles: rendezvous leg */
  double *big = malloc(big_n * sizeof(double));
  double *bigr = malloc(big_n * sizeof(double));
  srand(rank * 7 + 13);
  for (int it = 0; it < iters; it++) {
    int right = (rank + 1) % size, left = (rank + size - 1) % size;
    /* overlapping nonblocking collective */
    long vsum = rank + it, out = -1;
    MPI_Request creq;
    MPI_Iallreduce(&vsum, &out, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD,
                   &creq);
    /* random-size pt2pt ring (mixes eager and rendezvous) */
    size_t n = (rand() % 3 == 0) ? big_n : (size_t)(1 + rand() % 4096);
    for (size_t i = 0; i < n && i < big_n; i++)
      big[i] = rank * 1.0 + it + i % 101;
    MPI_Request rr, sr;
    MPI_Irecv(bigr, (int)big_n, MPI_DOUBLE, left, it, MPI_COMM_WORLD,
              &rr);
    MPI_Isend(big, (int)n, MPI_DOUBLE, right, it, MPI_COMM_WORLD, &sr);
    /* RMA into rank 0 under the epoch-free lock/unlock cycle */
    long long one = 1;
    MPI_Win_lock(MPI_LOCK_SHARED, 0, 0, win);
    MPI_Accumulate(&one, 1, MPI_LONG, 0, 0, 1, MPI_LONG, MPI_SUM, win);
    MPI_Win_unlock(0, win);
    MPI_Status st;
    MPI_Wait(&sr, MPI_STATUS_IGNORE);
    MPI_Wait(&rr, &st);
    int got = -1;
    MPI_Get_count(&st, MPI_DOUBLE, &got);
    /* validate the neighbor payload */
    for (int i = 0; i < got; i += 997)
      if (bigr[i] != left * 1.0 + it + i % 101) {
        fprintf(stderr, "[%d] corrupt at it %d i %d\n", rank, it, i);
        return 3;
      }
    MPI_Wait(&creq, MPI_STATUS_IGNORE);
    long expect = 0;
    for (int r = 0; r < size; r++) expect += r + it;
    if (out != expect) { fprintf(stderr, "bad allreduce\n"); return 4; }
  }
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0 && cell != (long long)size * iters) {
    fprintf(stderr, "bad rma tally %lld\n", cell);
    return 5;
  }
  MPI_Win_free(&win);
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("smsoak OK (%d iters, %d ranks)\n", iters, size);
  free(big); free(bigr);
  MPI_Finalize();
  return 0;
}
