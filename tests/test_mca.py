"""Tests for the MCA substrate: var precedence, component selection.

Mirrors the reference's var-system semantics (opal/mca/base/mca_base_var.c):
default < file < env < API precedence with per-var source tracking, and the
include/exclude component-list parsing of mca_base_component_find.c.
"""

import os

import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.mca import component as mca_comp
from zhpe_ompi_tpu.mca import var as mca_var


class TestVarSystem:
    def test_default(self):
        v = mca_var.register("t_default_param", 42, "test", type=int)
        assert v.value == 42
        assert v.source == mca_var.VarSource.DEFAULT

    def test_env_overrides_default(self, monkeypatch):
        monkeypatch.setenv("ZMPI_MCA_t_env_param", "7")
        v = mca_var.register("t_env_param", 1, "test", type=int)
        assert v.value == 7
        assert v.source == mca_var.VarSource.ENV

    def test_api_overrides_env(self, monkeypatch):
        monkeypatch.setenv("ZMPI_MCA_t_api_param", "7")
        mca_var.register("t_api_param", 1, "test", type=int)
        mca_var.set_var("t_api_param", 9)
        v = mca_var.lookup("t_api_param")
        assert v.value == 9
        assert v.source == mca_var.VarSource.API
        mca_var.unset("t_api_param")
        assert v.value == 7
        assert v.source == mca_var.VarSource.ENV

    def test_pending_api_set_before_register(self):
        mca_var.set_var("t_pending_param", "xyz")
        v = mca_var.register("t_pending_param", "abc", "test")
        assert v.value == "xyz"
        assert v.source == mca_var.VarSource.API

    def test_bool_parsing(self, monkeypatch):
        monkeypatch.setenv("ZMPI_MCA_t_bool_param", "yes")
        v = mca_var.register("t_bool_param", False, "test", type=bool)
        assert v.value is True

    def test_enum_rejects(self):
        mca_var.register("t_enum_param", "a", "test", enum=("a", "b"))
        with pytest.raises(ValueError):
            mca_var.set_var("t_enum_param", "c")

    def test_int_parses_hex(self, monkeypatch):
        monkeypatch.setenv("ZMPI_MCA_t_hex_param", "0x10")
        v = mca_var.register("t_hex_param", 0, "test", type=int)
        assert v.value == 16

    def test_not_settable(self):
        mca_var.register("t_ro_param", 5, "test", type=int, settable=False)
        with pytest.raises(PermissionError):
            mca_var.set_var("t_ro_param", 6)

    def test_file_layer(self, tmp_path, monkeypatch):
        conf = tmp_path / "mca-params.conf"
        conf.write_text("# comment\nt_file_param = hello\n")
        monkeypatch.setattr(mca_var, "PARAM_FILE", str(conf))
        reg = mca_var.VarRegistry()
        v = reg.register("t_file_param", "default", "test")
        assert v.value == "hello"
        assert v.source == mca_var.VarSource.FILE

    def test_override_file_beats_api(self, tmp_path, monkeypatch):
        ovr = tmp_path / "override.conf"
        ovr.write_text("t_ovr_param = pinned\n")
        monkeypatch.setattr(mca_var, "OVERRIDE_FILE", str(ovr))
        reg = mca_var.VarRegistry()
        v = reg.register("t_ovr_param", "default", "test")
        assert v.value == "pinned"
        assert v.source == mca_var.VarSource.OVERRIDE
        reg.set("t_ovr_param", "nope")
        assert v.value == "pinned"


class _FakeComp(mca_comp.Component):
    framework_name = "t_fw"

    def __init__(self, name, prio, avail=True):
        self.name = name
        self.default_priority = prio
        self._avail = avail
        super().__init__()

    def available(self):
        return self._avail


class TestComponentSelection:
    def _fw(self, name="t_fw"):
        fw = mca_comp.Framework(name)
        fw.register(_FakeComp("alpha", 50))
        fw.register(_FakeComp("beta", 80))
        fw.register(_FakeComp("gamma", 10))
        fw.register(_FakeComp("broken", 99, avail=False))
        return fw

    def test_priority_order(self):
        fw = self._fw()
        names = [c.name for c in fw.admitted()]
        assert names == ["beta", "alpha", "gamma"]

    def test_include_list(self, monkeypatch):
        fw = self._fw()
        mca_var.set_var("t_fw", "alpha,gamma")
        try:
            names = [c.name for c in fw.admitted()]
            assert names == ["alpha", "gamma"]
        finally:
            mca_var.unset("t_fw")

    def test_exclude_list(self):
        fw = self._fw()
        mca_var.set_var("t_fw", "^beta")
        try:
            names = [c.name for c in fw.admitted()]
            assert names == ["alpha", "gamma"]
        finally:
            mca_var.unset("t_fw")

    def test_mixed_raises(self):
        with pytest.raises(errors.ArgError):
            mca_comp.parse_include_exclude("a,^b")

    def test_exclude_caret_on_every_item(self):
        inc, exc = mca_comp.parse_include_exclude("^a,^b")
        assert inc is None and exc == {"a", "b"}

    def test_unset_preserves_override(self, tmp_path, monkeypatch):
        ovr = tmp_path / "override.conf"
        ovr.write_text("t_ovr2_param = pinned\n")
        monkeypatch.setattr(mca_var, "OVERRIDE_FILE", str(ovr))
        reg = mca_var.VarRegistry()
        v = reg.register("t_ovr2_param", "default", "test")
        reg.unset("t_ovr2_param")
        assert v.value == "pinned"
        assert v.source == mca_var.VarSource.OVERRIDE

    def test_select_one(self):
        fw = self._fw()
        assert fw.select_one().name == "beta"

    def test_priority_var_override(self):
        fw = self._fw()
        mca_var.set_var("t_fw_gamma_priority", 1000)
        try:
            assert fw.select_one().name == "gamma"
        finally:
            mca_var.unset("t_fw_gamma_priority")

    def test_info_dump(self):
        fw = mca_comp.framework("t_fw_info", "test framework")
        fw.register(_FakeComp("only", 1))
        dump = mca_comp.info()
        entry = [d for d in dump if d["framework"] == "t_fw_info"][0]
        assert entry["components"][0]["name"] == "only"
