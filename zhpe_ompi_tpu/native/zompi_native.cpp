// zompi native runtime kernels.
//
// Native-equivalent (C++) components for the host-plane hot paths, mirroring
// where the reference is native C (SURVEY.md §2.1): the datatype convertor
// (opal/datatype/opal_convertor.c:218-276 — segment-walking pack/unpack), the
// reduction op kernel table (ompi/mca/op/base/op_base_functions.c,
// ompi_op_base_functions[OP_MAX][TYPE_MAX]), and the receive-side tag-matching
// engine (ompi/mca/pml/ob1/pml_ob1_recvfrag.c:295-513).
//
// Exposed as a flat C ABI consumed via ctypes (no pybind11 in the image).
// The TPU compute path never touches this library — XLA owns device memory;
// these kernels serve the host plane (out-of-band transport, MPI_Pack
// semantics, host-side reductions in rendezvous protocols).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <type_traits>
#include <deque>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Datatype convertor: segment-based pack/unpack.
//
// `segs` is a flat array of nsegs (displacement, nbytes) int64 pairs — the
// optimized description (maximal contiguous runs) of ONE element of the
// datatype, cf. opal_datatype_optimize.c. `extent` strides elements.
// ---------------------------------------------------------------------------

void zompi_pack(const uint8_t* src, uint8_t* dst, const int64_t* segs,
                int64_t nsegs, int64_t extent, int64_t count) {
  for (int64_t e = 0; e < count; ++e) {
    const uint8_t* base = src + e * extent;
    for (int64_t s = 0; s < nsegs; ++s) {
      const int64_t disp = segs[2 * s], nb = segs[2 * s + 1];
      std::memcpy(dst, base + disp, static_cast<size_t>(nb));
      dst += nb;
    }
  }
}

void zompi_unpack(const uint8_t* src, uint8_t* dst, const int64_t* segs,
                  int64_t nsegs, int64_t extent, int64_t count) {
  for (int64_t e = 0; e < count; ++e) {
    uint8_t* base = dst + e * extent;
    for (int64_t s = 0; s < nsegs; ++s) {
      const int64_t disp = segs[2 * s], nb = segs[2 * s + 1];
      std::memcpy(base + disp, src, static_cast<size_t>(nb));
      src += nb;
    }
  }
}

// Resumable pack: emit packed bytes [position, position+max_bytes) of the
// packed stream (MPI_Pack / convertor-with-position semantics,
// test/datatype/position.c). Returns the new position.
int64_t zompi_pack_partial(const uint8_t* src, uint8_t* dst,
                           const int64_t* segs, int64_t nsegs, int64_t extent,
                           int64_t count, int64_t position,
                           int64_t max_bytes) {
  int64_t elem_size = 0;
  for (int64_t s = 0; s < nsegs; ++s) elem_size += segs[2 * s + 1];
  if (elem_size == 0) return position;
  int64_t remaining = max_bytes;
  int64_t pos = position;
  while (remaining > 0 && pos < elem_size * count) {
    const int64_t e = pos / elem_size;
    int64_t off = pos % elem_size;  // offset into this element's packed bytes
    const uint8_t* base = src + e * extent;
    for (int64_t s = 0; s < nsegs && remaining > 0; ++s) {
      const int64_t disp = segs[2 * s], nb = segs[2 * s + 1];
      if (off >= nb) {
        off -= nb;
        continue;
      }
      const int64_t take = std::min(nb - off, remaining);
      std::memcpy(dst, base + disp + off, static_cast<size_t>(take));
      dst += take;
      pos += take;
      remaining -= take;
      off = 0;
    }
  }
  return pos;
}

// Resumable unpack of a chunk landing at packed-byte `position` (chunks may
// arrive out of order, cf. test/datatype/unpack_ooo.c). Returns new position.
int64_t zompi_unpack_partial(const uint8_t* src, int64_t nbytes, uint8_t* dst,
                             const int64_t* segs, int64_t nsegs,
                             int64_t extent, int64_t count, int64_t position) {
  int64_t elem_size = 0;
  for (int64_t s = 0; s < nsegs; ++s) elem_size += segs[2 * s + 1];
  if (elem_size == 0) return position;
  int64_t remaining = nbytes;
  int64_t pos = position;
  while (remaining > 0 && pos < elem_size * count) {
    const int64_t e = pos / elem_size;
    int64_t off = pos % elem_size;
    uint8_t* base = dst + e * extent;
    for (int64_t s = 0; s < nsegs && remaining > 0; ++s) {
      const int64_t disp = segs[2 * s], nb = segs[2 * s + 1];
      if (off >= nb) {
        off -= nb;
        continue;
      }
      const int64_t take = std::min(nb - off, remaining);
      std::memcpy(base + disp + off, src, static_cast<size_t>(take));
      src += take;
      pos += take;
      remaining -= take;
      off = 0;
    }
  }
  return pos;
}

// ---------------------------------------------------------------------------
// Reduction op kernels: the ompi_op_base_functions[op][type] table as a
// compile-time template expansion. inout[i] = combine(in[i], inout[i])
// (MPI_Reduce source/target order, ompi/op/op.h:547-605).
// ---------------------------------------------------------------------------

enum ZompiOp {
  ZOMPI_OP_SUM = 0,
  ZOMPI_OP_PROD = 1,
  ZOMPI_OP_MAX = 2,
  ZOMPI_OP_MIN = 3,
  ZOMPI_OP_BAND = 4,
  ZOMPI_OP_BOR = 5,
  ZOMPI_OP_BXOR = 6,
  ZOMPI_OP_LAND = 7,
  ZOMPI_OP_LOR = 8,
  ZOMPI_OP_LXOR = 9,
};

enum ZompiType {
  ZOMPI_T_I8 = 0,
  ZOMPI_T_U8 = 1,
  ZOMPI_T_I16 = 2,
  ZOMPI_T_U16 = 3,
  ZOMPI_T_I32 = 4,
  ZOMPI_T_U32 = 5,
  ZOMPI_T_I64 = 6,
  ZOMPI_T_U64 = 7,
  ZOMPI_T_F32 = 8,
  ZOMPI_T_F64 = 9,
};

}  // extern "C"

namespace {

template <typename T>
void reduce_typed(int op, const T* in, T* inout, int64_t n, bool is_integer) {
  switch (op) {
    case ZOMPI_OP_SUM:
      for (int64_t i = 0; i < n; ++i) inout[i] = in[i] + inout[i];
      break;
    case ZOMPI_OP_PROD:
      for (int64_t i = 0; i < n; ++i) inout[i] = in[i] * inout[i];
      break;
    case ZOMPI_OP_MAX:
      // NaN propagates, matching np.maximum (either operand NaN → NaN)
      for (int64_t i = 0; i < n; ++i) {
        if constexpr (std::is_floating_point_v<T>) {
          inout[i] =
              (in[i] > inout[i] || std::isnan(in[i])) ? in[i] : inout[i];
        } else {
          inout[i] = in[i] > inout[i] ? in[i] : inout[i];
        }
      }
      break;
    case ZOMPI_OP_MIN:
      for (int64_t i = 0; i < n; ++i) {
        if constexpr (std::is_floating_point_v<T>) {
          inout[i] =
              (in[i] < inout[i] || std::isnan(in[i])) ? in[i] : inout[i];
        } else {
          inout[i] = in[i] < inout[i] ? in[i] : inout[i];
        }
      }
      break;
    case ZOMPI_OP_LAND:
      for (int64_t i = 0; i < n; ++i)
        inout[i] = static_cast<T>((in[i] != T(0)) && (inout[i] != T(0)));
      break;
    case ZOMPI_OP_LOR:
      for (int64_t i = 0; i < n; ++i)
        inout[i] = static_cast<T>((in[i] != T(0)) || (inout[i] != T(0)));
      break;
    case ZOMPI_OP_LXOR:
      for (int64_t i = 0; i < n; ++i)
        inout[i] = static_cast<T>((in[i] != T(0)) != (inout[i] != T(0)));
      break;
    default:
      (void)is_integer;
      break;
  }
}

template <typename T>
void reduce_bitwise(int op, const T* in, T* inout, int64_t n) {
  switch (op) {
    case ZOMPI_OP_BAND:
      for (int64_t i = 0; i < n; ++i) inout[i] = in[i] & inout[i];
      break;
    case ZOMPI_OP_BOR:
      for (int64_t i = 0; i < n; ++i) inout[i] = in[i] | inout[i];
      break;
    case ZOMPI_OP_BXOR:
      for (int64_t i = 0; i < n; ++i) inout[i] = in[i] ^ inout[i];
      break;
    default:
      break;
  }
}

template <typename T>
int reduce_dispatch_int(int op, const void* in, void* inout, int64_t n) {
  if (op >= ZOMPI_OP_BAND && op <= ZOMPI_OP_BXOR) {
    reduce_bitwise<T>(op, static_cast<const T*>(in), static_cast<T*>(inout), n);
  } else {
    reduce_typed<T>(op, static_cast<const T*>(in), static_cast<T*>(inout), n,
                    true);
  }
  return 0;
}

template <typename T>
int reduce_dispatch_float(int op, const void* in, void* inout, int64_t n) {
  if (op >= ZOMPI_OP_BAND && op <= ZOMPI_OP_BXOR) return -1;  // no bitwise
  reduce_typed<T>(op, static_cast<const T*>(in), static_cast<T*>(inout), n,
                  false);
  return 0;
}

}  // namespace

extern "C" {

// Returns 0 on success, -1 for an undefined (op, type) pair — the caller
// falls back to the Python path (mirrors the reference's NULL table slots).
int zompi_reduce(int op, int type, const void* in, void* inout, int64_t n) {
  if (op < ZOMPI_OP_SUM || op > ZOMPI_OP_LXOR) return -1;  // unknown op code
  switch (type) {
    case ZOMPI_T_I8:
      return reduce_dispatch_int<int8_t>(op, in, inout, n);
    case ZOMPI_T_U8:
      return reduce_dispatch_int<uint8_t>(op, in, inout, n);
    case ZOMPI_T_I16:
      return reduce_dispatch_int<int16_t>(op, in, inout, n);
    case ZOMPI_T_U16:
      return reduce_dispatch_int<uint16_t>(op, in, inout, n);
    case ZOMPI_T_I32:
      return reduce_dispatch_int<int32_t>(op, in, inout, n);
    case ZOMPI_T_U32:
      return reduce_dispatch_int<uint32_t>(op, in, inout, n);
    case ZOMPI_T_I64:
      return reduce_dispatch_int<int64_t>(op, in, inout, n);
    case ZOMPI_T_U64:
      return reduce_dispatch_int<uint64_t>(op, in, inout, n);
    case ZOMPI_T_F32:
      return reduce_dispatch_float<float>(op, in, inout, n);
    case ZOMPI_T_F64:
      return reduce_dispatch_float<double>(op, in, inout, n);
    default:
      return -1;
  }
}

// ---------------------------------------------------------------------------
// Tag-matching engine (pml_ob1_recvfrag.c:295-513): posted-receive list +
// unexpected-message queue with MPI wildcard semantics. Payloads and request
// callbacks live on the Python side, referenced here by opaque uint64 keys.
// ---------------------------------------------------------------------------

struct ZompiEnvelope {
  int64_t src, tag, cid, seq;
  uint64_t payload_key;
};

struct ZompiPosted {
  int64_t src, tag, cid;  // src/tag may be -1 (ANY)
  uint64_t req_key;
};

struct ZompiMatch {
  std::mutex mu;
  std::deque<ZompiPosted> posted;
  std::deque<ZompiEnvelope> unexpected;
};

static inline bool zompi_matches(const ZompiPosted& p, const ZompiEnvelope& e) {
  if (p.cid != e.cid) return false;
  if (p.src != -1 && p.src != e.src) return false;
  if (p.tag != -1 && p.tag != e.tag) return false;
  return true;
}

void* zompi_match_create() { return new ZompiMatch(); }

void zompi_match_destroy(void* h) { delete static_cast<ZompiMatch*>(h); }

// Post a receive. Returns 1 and fills out_env[4]={src,tag,cid,seq} +
// *out_payload_key if an unexpected message matched (earliest wins), else 0.
int zompi_match_post(void* h, int64_t src, int64_t tag, int64_t cid,
                     uint64_t req_key, int64_t* out_env,
                     uint64_t* out_payload_key) {
  ZompiMatch* m = static_cast<ZompiMatch*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  ZompiPosted p{src, tag, cid, req_key};
  for (auto it = m->unexpected.begin(); it != m->unexpected.end(); ++it) {
    if (zompi_matches(p, *it)) {
      out_env[0] = it->src;
      out_env[1] = it->tag;
      out_env[2] = it->cid;
      out_env[3] = it->seq;
      *out_payload_key = it->payload_key;
      m->unexpected.erase(it);
      return 1;
    }
  }
  m->posted.push_back(p);
  return 0;
}

// Deliver an arriving message. Returns 1 and fills *out_req_key if a posted
// receive matched (earliest wins), else 0 (parked on the unexpected queue).
int zompi_match_incoming(void* h, int64_t src, int64_t tag, int64_t cid,
                         int64_t seq, uint64_t payload_key,
                         uint64_t* out_req_key) {
  ZompiMatch* m = static_cast<ZompiMatch*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  ZompiEnvelope e{src, tag, cid, seq, payload_key};
  for (auto it = m->posted.begin(); it != m->posted.end(); ++it) {
    if (zompi_matches(*it, e)) {
      *out_req_key = it->req_key;
      m->posted.erase(it);
      return 1;
    }
  }
  m->unexpected.push_back(e);
  return 0;
}

// MPI_Iprobe: peek the earliest matching unexpected envelope (no dequeue).
int zompi_match_probe(void* h, int64_t src, int64_t tag, int64_t cid,
                      int64_t* out_env) {
  ZompiMatch* m = static_cast<ZompiMatch*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  ZompiPosted p{src, tag, cid, 0};
  for (const auto& e : m->unexpected) {
    if (zompi_matches(p, e)) {
      out_env[0] = e.src;
      out_env[1] = e.tag;
      out_env[2] = e.cid;
      out_env[3] = e.seq;
      return 1;
    }
  }
  return 0;
}

// MPI_Mprobe: dequeue the earliest matching unexpected envelope — the
// returned message is matched and can no longer satisfy other receives.
int zompi_match_extract(void* h, int64_t src, int64_t tag, int64_t cid,
                        int64_t* out_env, uint64_t* out_payload_key) {
  ZompiMatch* m = static_cast<ZompiMatch*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  ZompiPosted p{src, tag, cid, 0};
  for (auto it = m->unexpected.begin(); it != m->unexpected.end(); ++it) {
    if (zompi_matches(p, *it)) {
      out_env[0] = it->src;
      out_env[1] = it->tag;
      out_env[2] = it->cid;
      out_env[3] = it->seq;
      *out_payload_key = it->payload_key;
      m->unexpected.erase(it);
      return 1;
    }
  }
  return 0;
}

void zompi_match_stats(void* h, int64_t* n_posted, int64_t* n_unexpected) {
  ZompiMatch* m = static_cast<ZompiMatch*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  *n_posted = static_cast<int64_t>(m->posted.size());
  *n_unexpected = static_cast<int64_t>(m->unexpected.size());
}

// Queue depths excluding entries attributable to the given sources or
// communicator ids: posted receives NAMED on an excluded source
// (abandoned after a typed process failure) or posted on an excluded
// cid (a revoked channel never delivers again), and unexpected
// messages FROM an excluded source or carried on an excluded cid.  The
// checkpoint quiescence check uses this so acked-failed peers' and
// revoked channels' rows — which no drain can ever clear — don't block
// a recovery-time snapshot.  ANY_SOURCE (-1) posted receives are
// unattributable by source and counted unless their cid is excluded.
void zompi_match_stats_excluding(void* h, const int64_t* excl_srcs,
                                 int64_t n_excl, const int64_t* excl_cids,
                                 int64_t n_cids, int64_t* n_posted,
                                 int64_t* n_unexpected) {
  ZompiMatch* m = static_cast<ZompiMatch*>(h);
  std::lock_guard<std::mutex> g(m->mu);
  auto excluded = [&](int64_t src, int64_t cid) {
    for (int64_t i = 0; i < n_excl; ++i)
      if (excl_srcs[i] == src) return true;
    for (int64_t i = 0; i < n_cids; ++i)
      if (excl_cids[i] == cid) return true;
    return false;
  };
  int64_t p = 0, u = 0;
  for (const auto& r : m->posted)
    if (!excluded(r.src, r.cid)) ++p;
  for (const auto& e : m->unexpected)
    if (!excluded(e.src, e.cid)) ++u;
  *n_posted = p;
  *n_unexpected = u;
}

// ---------------------------------------------------------------------------
// Cross-process atomics on mapped symmetric segments.
//
// The oshmem atomic framework executes AMOs in native code against the
// mapped segment (oshmem/mca/atomic/basic over sshmem/mmap); __atomic
// builtins give lock-free 1/2/4/8-byte read-modify-write that is coherent
// across OS processes sharing the mapping.  Floats go through bit-punned
// compare-exchange loops (CAS compares BITS, so -0.0 vs 0.0 and NaN
// payloads follow bit equality, not IEEE ==; the OpenSHMEM AMO set is
// integer-centric and this matches practical usage).
//
// kind: 0=add 1=swap 2=cas 3=set 4=fetch.  The pre-op value is always
// written to old_*.  Returns 0 ok, -1 unsupported type for native AMO.
// ---------------------------------------------------------------------------

}  // extern "C"  (templates below need C++ linkage)

namespace {

template <typename T>
void amo_int(T* p, int kind, T val, T cmp, T* old) {
  switch (kind) {
    case 0: *old = __atomic_fetch_add(p, val, __ATOMIC_SEQ_CST); break;
    case 1: *old = __atomic_exchange_n(p, val, __ATOMIC_SEQ_CST); break;
    case 2: {
      T expected = cmp;
      __atomic_compare_exchange_n(p, &expected, val, false,
                                  __ATOMIC_SEQ_CST, __ATOMIC_SEQ_CST);
      *old = expected;  // on failure holds the current value = pre-op
      break;
    }
    case 3: *old = __atomic_exchange_n(p, val, __ATOMIC_SEQ_CST); break;
    case 4: *old = __atomic_load_n(p, __ATOMIC_SEQ_CST); break;
  }
}

template <typename F, typename Bits>
void amo_float(F* p, int kind, F val, F cmp, F* old) {
  static_assert(sizeof(F) == sizeof(Bits), "pun width");
  Bits* bp = reinterpret_cast<Bits*>(p);
  auto pun = [](F f) { Bits b; std::memcpy(&b, &f, sizeof b); return b; };
  auto unpun = [](Bits b) { F f; std::memcpy(&f, &b, sizeof f); return f; };
  switch (kind) {
    case 0: {  // add: CAS loop
      Bits cur = __atomic_load_n(bp, __ATOMIC_SEQ_CST);
      for (;;) {
        F next = unpun(cur) + val;
        Bits nb = pun(next);
        if (__atomic_compare_exchange_n(bp, &cur, nb, false,
                                        __ATOMIC_SEQ_CST, __ATOMIC_SEQ_CST))
          break;
      }
      *old = unpun(cur);
      break;
    }
    case 1:
    case 3:
      *old = unpun(__atomic_exchange_n(bp, pun(val), __ATOMIC_SEQ_CST));
      break;
    case 2: {
      Bits expected = pun(cmp);
      __atomic_compare_exchange_n(bp, &expected, pun(val), false,
                                  __ATOMIC_SEQ_CST, __ATOMIC_SEQ_CST);
      *old = unpun(expected);
      break;
    }
    case 4: *old = unpun(__atomic_load_n(bp, __ATOMIC_SEQ_CST)); break;
  }
}

}  // namespace

extern "C" {

int zompi_shm_amo(void* addr, int type_code, int kind, int64_t value_i,
                  int64_t cmp_i, double value_f, double cmp_f,
                  int64_t* old_i, double* old_f) {
  switch (type_code) {
    case 0: {  // int8
      int8_t o;
      amo_int<int8_t>((int8_t*)addr, kind, (int8_t)value_i, (int8_t)cmp_i, &o);
      *old_i = o; return 0;
    }
    case 1: {  // uint8
      uint8_t o;
      amo_int<uint8_t>((uint8_t*)addr, kind, (uint8_t)value_i,
                       (uint8_t)cmp_i, &o);
      *old_i = (int64_t)o; return 0;
    }
    case 2: {  // int16
      int16_t o;
      amo_int<int16_t>((int16_t*)addr, kind, (int16_t)value_i,
                       (int16_t)cmp_i, &o);
      *old_i = o; return 0;
    }
    case 3: {  // uint16
      uint16_t o;
      amo_int<uint16_t>((uint16_t*)addr, kind, (uint16_t)value_i,
                        (uint16_t)cmp_i, &o);
      *old_i = (int64_t)o; return 0;
    }
    case 4: {  // int32
      int32_t o;
      amo_int<int32_t>((int32_t*)addr, kind, (int32_t)value_i,
                       (int32_t)cmp_i, &o);
      *old_i = o; return 0;
    }
    case 5: {  // uint32
      uint32_t o;
      amo_int<uint32_t>((uint32_t*)addr, kind, (uint32_t)value_i,
                        (uint32_t)cmp_i, &o);
      *old_i = (int64_t)o; return 0;
    }
    case 6: {  // int64
      int64_t o;
      amo_int<int64_t>((int64_t*)addr, kind, value_i, cmp_i, &o);
      *old_i = o; return 0;
    }
    case 7: {  // uint64
      uint64_t o;
      amo_int<uint64_t>((uint64_t*)addr, kind, (uint64_t)value_i,
                        (uint64_t)cmp_i, &o);
      *old_i = (int64_t)o; return 0;
    }
    case 8: {  // float32
      float o;
      amo_float<float, uint32_t>((float*)addr, kind, (float)value_f,
                                 (float)cmp_f, &o);
      *old_f = o; return 0;
    }
    case 9: {  // float64
      double o;
      amo_float<double, uint64_t>((double*)addr, kind, value_f, cmp_f, &o);
      *old_f = o; return 0;
    }
  }
  return -1;
}

// Full memory fence: shmem_quiet/fence ordering point for mapped segments.
void zompi_shm_fence() { __atomic_thread_fence(__ATOMIC_SEQ_CST); }

int zompi_abi_version() { return 3; }

}  // extern "C"
