"""DSS serialization tests (reference: opal/dss, test/dss/*)."""

import numpy as np
import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.utils import dss


class TestRoundtrip:
    @pytest.mark.parametrize("value", [
        None, True, False, 0, 1, -1, 127, 128, -300, 2**40, -(2**40),
        0.0, -1.5, 3.14159, "", "hello", "unicode: émojis 🎉",
        b"", b"\x00\xff raw",
    ])
    def test_scalars(self, value):
        [out] = dss.unpack(dss.pack(value))
        assert out == value and type(out) is type(value)

    def test_multiple_values(self):
        vals = [1, "two", b"three", 4.0, None]
        assert dss.unpack(dss.pack(*vals)) == vals

    @pytest.mark.parametrize("dtype", [
        np.int8, np.int32, np.int64, np.uint16, np.float32, np.float64,
        np.bool_,
    ])
    def test_ndarray(self, dtype):
        arr = np.arange(24).reshape(2, 3, 4).astype(dtype)
        [out] = dss.unpack(dss.pack(arr))
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)

    def test_ndarray_zero_size(self):
        arr = np.zeros((0, 5), np.float32)
        [out] = dss.unpack(dss.pack(arr))
        assert out.shape == (0, 5)

    def test_numpy_scalar(self):
        [out] = dss.unpack(dss.pack(np.float32(2.5)))
        assert out.dtype == np.float32 and float(out) == 2.5

    def test_nested_containers(self):
        obj = {
            "config": {"ranks": [0, 1, 2], "mesh": (2, 4)},
            "weights": np.linspace(0, 1, 7).astype(np.float32),
            ("tuple", "key"): [b"payload", None, {"deep": True}],
        }
        [out] = dss.unpack(dss.pack(obj))
        assert out["config"] == obj["config"]
        assert isinstance(out["config"]["mesh"], tuple)
        np.testing.assert_array_equal(out["weights"], obj["weights"])
        assert out[("tuple", "key")][2] == {"deep": True}

    def test_unpackable_type_raises(self):
        with pytest.raises(errors.TypeError_):
            dss.pack(object())

    def test_trailing_garbage_raises(self):
        with pytest.raises(errors.TruncateError):
            dss.unpack(dss.pack(1) + b"\x00")

    def test_wire_is_compact(self):
        # a small int should be a handful of bytes, not a pickle blob
        assert len(dss.pack(7)) <= 4
