"""``zmpi-checkpoint`` — the opal-checkpoint / opal-restart CLI analog.

The reference ships command-line checkpoint tooling
(``opal/tools/opal-checkpoint``, ``opal-restart``) on top of its crs
framework.  This CLI is that surface over the framework's async
checkpointer (``runtime/checkpoint.py``):

    python -m zhpe_ompi_tpu.tools.checkpoint list <dir>
    python -m zhpe_ompi_tpu.tools.checkpoint inspect <dir> [--step N]
    python -m zhpe_ompi_tpu.tools.checkpoint prune <dir> --keep K

``list`` prints available steps; ``inspect`` loads one snapshot on CPU
and prints its tree structure (leaf shapes/dtypes); ``prune`` applies
the retention policy offline (the opal-checkpoint -s housekeeping role).
Restore-into-a-program stays programmatic (``Checkpointer.restore``) —
process-image restart does not transfer to this platform; the snapshot
IS the restartable state.
"""

from __future__ import annotations

import argparse
import os
import sys


def _list(directory: str) -> int:
    from ..runtime.checkpoint import Checkpointer

    ck = Checkpointer(directory)
    steps = ck.all_steps()
    if not steps:
        print(f"no checkpoints in {directory}")
        return 1
    for s in steps:
        d = os.path.join(directory, f"step_{s}")
        size = 0
        if os.path.isdir(d):
            size = sum(
                os.path.getsize(os.path.join(d, f)) for f in os.listdir(d)
            )
        print(f"step {s:8d}  {size / 1e6:8.2f} MB")
    print(f"latest: {ck.latest_step()}")
    return 0


def _inspect(directory: str, step: int | None) -> int:
    import jax

    from ..runtime.checkpoint import Checkpointer

    jax.config.update("jax_platforms", "cpu")
    ck = Checkpointer(directory)
    state = ck.restore(step)
    step = step if step is not None else ck.latest_step()
    print(f"step {step}:")
    leaves, treedef = jax.tree_util.tree_flatten(state)
    print(f"  tree: {treedef}")
    total = 0
    for i, leaf in enumerate(leaves):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        nbytes = getattr(leaf, "nbytes", 0)
        total += nbytes
        print(f"  leaf[{i}]: shape={tuple(shape)} dtype={dtype}")
    print(f"  total: {total / 1e6:.2f} MB in {len(leaves)} leaves")
    return 0


def _prune(directory: str, keep: int) -> int:
    import shutil

    from ..runtime.checkpoint import Checkpointer

    ck = Checkpointer(directory)
    steps = ck.all_steps()
    # --keep 0 means keep none: drop every step (steps[:-0] would be []).
    drop = steps[:-keep] if keep > 0 else list(steps)
    for s in drop:
        shutil.rmtree(os.path.join(directory, f"step_{s}"),
                      ignore_errors=True)
        print(f"pruned step {s}")
    print(f"kept {min(len(steps), keep)} of {len(steps)}")
    return 0


def main(args: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zmpi-checkpoint",
        description="Checkpoint housekeeping CLI (opal-checkpoint analog)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_list = sub.add_parser("list")
    p_list.add_argument("dir")
    p_ins = sub.add_parser("inspect")
    p_ins.add_argument("dir")
    p_ins.add_argument("--step", type=int, default=None)
    p_pr = sub.add_parser("prune")
    p_pr.add_argument("dir")
    p_pr.add_argument("--keep", type=int, required=True,
                      help="checkpoints to retain (0 prunes everything)")
    ns = ap.parse_args(args)
    if ns.cmd == "prune" and ns.keep < 0:
        ap.error(f"--keep must be >= 0, got {ns.keep}")
    if ns.cmd == "list":
        return _list(ns.dir)
    if ns.cmd == "inspect":
        return _inspect(ns.dir, ns.step)
    return _prune(ns.dir, ns.keep)


if __name__ == "__main__":
    sys.exit(main())
