"""Native (C++) kernel layer: build, and parity with the pure-Python paths.

Mirrors the reference's approach of testing the datatype engine without any
network (test/datatype/ddt_pack.c, position.c, unpack_ooo.c) — here
additionally cross-checking the C++ kernels against the numpy reference
implementations.
"""

import numpy as np
import pytest

from zhpe_ompi_tpu import native, ops
from zhpe_ompi_tpu.datatype import convertor, derived, predefined
from zhpe_ompi_tpu.pt2pt import matching


def test_native_builds():
    assert native.available(), f"native build failed: {native.build_error}"
    assert native.load().zompi_abi_version() == 3


@pytest.fixture
def vector_type():
    # 5 blocks of 3 float64s strided 7 elements apart
    return derived.create_vector(5, 3, 7, predefined.DOUBLE)


def _numpy_pack(buffer, datatype, count):
    view = buffer.reshape(-1).view(np.uint8)
    return view[convertor.byte_index_map(datatype, count)]


def test_pack_matches_numpy(vector_type):
    src = np.arange(7 * 5 * 4, dtype=np.float64)
    packed = convertor.pack(src, vector_type, 4)
    assert bytes(packed) == bytes(_numpy_pack(src, vector_type, 4))


def test_pack_unpack_roundtrip_struct():
    t = derived.create_struct(
        [2, 3], [0, 32], [predefined.INT32_T, predefined.DOUBLE]
    )
    count = 9
    src = np.random.default_rng(0).integers(
        0, 255, convertor.span_bytes(t, count), dtype=np.uint8
    ).astype(np.uint8)
    packed = convertor.pack(src, t, count)
    assert packed.nbytes == t.size * count
    out = convertor.unpack(packed, t, count)
    repacked = convertor.pack(out, t, count)
    assert bytes(repacked) == bytes(packed)


def test_pack_partial_native_matches_full(vector_type):
    src = np.arange(7 * 5 * 6, dtype=np.float64)
    full = convertor.pack(src, vector_type, 6)
    pos, chunks = 0, []
    # odd chunk size to split segment boundaries
    while pos < full.nbytes:
        chunk, pos = convertor.pack_partial(src, vector_type, 6, pos, 37)
        chunks.append(chunk)
    assert bytes(np.concatenate(chunks)) == bytes(full)


def test_unpack_partial_out_of_order(vector_type):
    count = 6
    src = np.arange(7 * 5 * count, dtype=np.float64)
    full = convertor.pack(src, vector_type, count)
    dest = np.zeros(convertor.span_bytes(vector_type, count), np.uint8)
    # deliver chunks in reverse order
    bounds = list(range(0, full.nbytes, 41)) + [full.nbytes]
    spans = list(zip(bounds[:-1], bounds[1:]))
    for lo, hi in reversed(spans):
        convertor.unpack_partial(full[lo:hi], dest, vector_type, count, lo)
    assert bytes(convertor.pack(dest, vector_type, count)) == bytes(full)


@pytest.mark.parametrize("opname", list(native.OP_CODES))
@pytest.mark.parametrize("dtype", ["int32", "uint64", "float64"])
def test_native_reduce_matches_numpy(opname, dtype):
    op = getattr(ops, opname.replace("MPI_", ""))
    if np.dtype(dtype).kind == "f" and op.allowed_kinds == "iub":
        pytest.skip(f"{opname} undefined for float types")
    rng = np.random.default_rng(3)
    if np.dtype(dtype).kind == "f":
        a = rng.normal(size=5000).astype(dtype)
        b = rng.normal(size=5000).astype(dtype)
    else:
        a = rng.integers(0, 100, 5000).astype(dtype)
        b = rng.integers(0, 100, 5000).astype(dtype)
    got = op(a, b)  # size >= 4096 → native path
    want = op(a[:1], b[:1])  # scalar-size → numpy path
    np.testing.assert_array_equal(got[:1], want)
    # full parity against the raw numpy fn
    np.testing.assert_array_equal(got, op._np_fn(a, b))


def test_native_max_propagates_nan():
    # np.maximum propagates NaN; the native kernel must agree on both sides
    # of the size threshold (regression: size-dependent NaN semantics).
    a = np.full(5000, np.nan, np.float32)
    b = np.zeros(5000, np.float32)
    assert np.isnan(ops.MAX(a, b)).all()
    assert np.isnan(ops.MAX(b, a)).all()
    assert np.isnan(ops.MIN(a, b)).all()


def test_pack_partial_rejects_short_buffer(vector_type):
    from zhpe_ompi_tpu.core import errors

    with pytest.raises(errors.TruncateError):
        convertor.pack_partial(np.zeros(8, np.uint8), vector_type, 4, 0, 10**6)
    with pytest.raises(errors.ArgError):
        convertor.pack_partial(
            np.zeros(convertor.span_bytes(vector_type, 4), np.uint8),
            vector_type, 4, -1, 16)


def test_unpack_partial_rejects_short_destination(vector_type):
    from zhpe_ompi_tpu.core import errors

    chunk = np.zeros(64, np.uint8)
    with pytest.raises(errors.TruncateError):
        convertor.unpack_partial(chunk, np.zeros(4, np.uint8), vector_type, 4, 0)
    dest = np.zeros(convertor.span_bytes(vector_type, 4), np.uint8)
    with pytest.raises(errors.ArgError):
        convertor.unpack_partial(chunk, dest, vector_type, 4, -1)


def test_native_reduce_preserves_operands():
    a = np.ones(5000, dtype=np.int32)
    b = np.full(5000, 7, dtype=np.int32)
    out = ops.SUM(a, b)
    assert b[0] == 7 and a[0] == 1 and out[0] == 8


class TestNativeMatching:
    def make(self):
        if not native.available():
            pytest.skip("no native lib")
        return matching.NativeMatchingEngine()

    def test_post_then_incoming(self):
        eng = self.make()
        hits = []
        eng.post_recv(1, 5, 0, lambda e, p: hits.append((e, p)))
        eng.incoming(matching.Envelope(1, 5, 0, 0), "payload")
        assert hits and hits[0][1] == "payload"
        assert eng.stats() == {"posted": 0, "unexpected": 0}

    def test_unexpected_then_post_wildcards(self):
        eng = self.make()
        eng.incoming(matching.Envelope(2, 9, 1, 0), "a")
        eng.incoming(matching.Envelope(3, 9, 1, 1), "b")
        assert eng.stats()["unexpected"] == 2
        got = []
        eng.post_recv(matching.ANY_SOURCE, 9, 1, lambda e, p: got.append(p))
        assert got == ["a"]  # earliest unexpected wins
        probe = eng.probe(matching.ANY_SOURCE, matching.ANY_TAG, 1)
        assert probe is not None and probe.src == 3

    def test_no_cross_cid_match(self):
        eng = self.make()
        got = []
        eng.post_recv(matching.ANY_SOURCE, matching.ANY_TAG, 7, got.append)
        eng.incoming(matching.Envelope(0, 0, 8, 0), "x")
        assert eng.stats() == {"posted": 1, "unexpected": 1}

    def test_parity_with_python_engine(self):
        rng = np.random.default_rng(0)
        neng, peng = self.make(), matching.MatchingEngine()
        nlog, plog = [], []
        events = []
        for i in range(200):
            kind = rng.integers(0, 2)
            src = int(rng.integers(-1, 3))
            tag = int(rng.integers(-1, 3))
            events.append((kind, src, tag, i))
        for kind, src, tag, i in events:
            if kind == 0:
                neng.post_recv(src, tag, 0, lambda e, p, i=i: nlog.append((i, e.seq, p)))
                peng.post_recv(src, tag, 0, lambda e, p, i=i: plog.append((i, e.seq, p)))
            else:
                env = matching.Envelope(max(src, 0), max(tag, 0), 0, i)
                neng.incoming(env, f"m{i}")
                peng.incoming(env, f"m{i}")
        assert nlog == plog
        assert neng.stats() == peng.stats()


class TestShmAmo:
    """Native cross-process AMOs (zompi_shm_amo): exercised on ordinary
    process memory here (the mapping case is tests/test_shmem_mmap.py)."""

    def _amo(self, arr, code, kind, vi=0, ci=0, vf=0.0, cf=0.0):
        import ctypes

        lib = native.load()
        oi = ctypes.c_int64(0)
        of = ctypes.c_double(0.0)
        rc = lib.zompi_shm_amo(
            ctypes.c_void_p(arr.ctypes.data), code, kind,
            vi, ci, vf, cf, ctypes.byref(oi), ctypes.byref(of),
        )
        assert rc == 0
        return oi.value, of.value

    def test_int64_add_swap_cas(self):
        a = np.array([10], dtype=np.int64)
        old, _ = self._amo(a, 6, 0, vi=5)       # add
        assert (old, a[0]) == (10, 15)
        old, _ = self._amo(a, 6, 1, vi=100)     # swap
        assert (old, a[0]) == (15, 100)
        old, _ = self._amo(a, 6, 2, vi=7, ci=100)  # cas hit
        assert (old, a[0]) == (100, 7)
        old, _ = self._amo(a, 6, 2, vi=9, ci=100)  # cas miss
        assert (old, a[0]) == (7, 7)
        old, _ = self._amo(a, 6, 4)             # fetch
        assert old == 7

    def test_float32_add_cas(self):
        a = np.array([1.5], dtype=np.float32)
        _, old = self._amo(a, 8, 0, vf=2.25)
        assert (old, float(a[0])) == (1.5, 3.75)
        _, old = self._amo(a, 8, 2, vf=9.0, cf=3.75)
        assert (old, float(a[0])) == (3.75, 9.0)

    def test_narrow_widths(self):
        for code, dt in [(0, np.int8), (2, np.int16), (4, np.int32),
                         (7, np.uint64)]:
            a = np.array([3], dtype=dt)
            old, _ = self._amo(a, code, 0, vi=4)
            assert (old, int(a[0])) == (3, 7)

    def test_concurrent_fetch_add_exact(self):
        import threading

        a = np.zeros(1, dtype=np.int64)
        ADDS, THREADS = 2000, 8

        def worker():
            for _ in range(ADDS):
                self._amo(a, 6, 0, vi=1)

        ts = [threading.Thread(target=worker) for _ in range(THREADS)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert a[0] == ADDS * THREADS


class TestNativeMatchingParityWildcards:
    """Binned-Python vs native-C parity on a wildcard-heavy multi-cid
    mix: delivery order, probe/extract results, stats, and
    stats_excluding's EXACT counts must agree event for event."""

    def test_parity_wildcard_mix_and_stats_excluding(self):
        if not native.available():
            pytest.skip("no native lib")
        rng = np.random.default_rng(7)
        neng, peng = (matching.NativeMatchingEngine(),
                      matching.MatchingEngine())
        nlog, plog = [], []
        for i in range(400):
            kind = int(rng.integers(0, 3))
            src = int(rng.integers(-1, 4))
            tag = int(rng.integers(-1, 3))
            cid = int(rng.integers(0, 3))
            if kind == 0:
                neng.post_recv(src, tag, cid,
                               lambda e, p, i=i: nlog.append(
                                   (i, e.src, e.seq, p)))
                peng.post_recv(src, tag, cid,
                               lambda e, p, i=i: plog.append(
                                   (i, e.src, e.seq, p)))
            elif kind == 1:
                env = matching.Envelope(max(src, 0), max(tag, 0), cid, i)
                neng.incoming(env, f"m{i}")
                peng.incoming(env, f"m{i}")
            else:
                ne = neng.extract(src, tag, cid)
                pe = peng.extract(src, tag, cid)
                assert (ne is None) == (pe is None)
                if ne is not None:
                    assert ne[0] == pe[0] and ne[1] == pe[1]
            assert neng.probe(src, tag, cid) == peng.probe(src, tag, cid)
        assert nlog == plog
        assert neng.stats() == peng.stats()
        for srcs, cids in (((0,), ()), ((1, 2), (0,)), ((), (1, 2)),
                           ((-1,), ()), ((0, 1, 2, 3), (0, 1, 2))):
            assert neng.stats_excluding(srcs, cids) == \
                peng.stats_excluding(srcs, cids), (srcs, cids)
