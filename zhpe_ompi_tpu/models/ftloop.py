"""Fault-tolerant training loop — the scenario the FT subsystem exists
for.

Every recovery mechanism this tree grew — typed classification (PRs
1/8/13), consensus shrink, checkpoint rollback, batched respawn over
the DVM, and now the device liveness probe (``parallel/mesh.py``) — is
plumbing; a TRAINING JOB surviving a fault is the product.  This module
is that product: a driver that runs an application step function under
the armed device-probe guard, checkpoints at quiescent points, and on
ANY typed fault — a transport death, a daemon waitpid event, a wedged
device — runs the full pipeline and resumes at full size:

    fault → failure_ack → consensus shrink → REMESH (the app re-shards
    onto the survivor endpoint) → rollback (checkpoint restore) →
    respawn → await rejoin → REMESH back to full size → resume

The loop contract::

    loop = FtTrainLoop(
        proc, step_fn=step, state=params,
        checkpointer=Checkpointer(dir), ckpt_every=2,
        probe=DeviceLivenessProbe(...),      # optional, opt-in
        wedge=plan.arm_device(rank, state),  # fault injection (tests)
        respawner=recovery.daemon_respawn,   # real processes
        remesh_fn=lambda ep, st: zopt.reshard(ep, opt_full),
    )
    state, losses = loop.run(steps)

``step_fn(ep, state, step_i) -> (state, loss)`` computes one training
step over the CURRENT endpoint (full size in steady state; the loop
never hands it a shrunken endpoint — remesh_fn owns the survivor-mesh
leg).  A replacement rank (``ZMPI_REJOIN=1``) constructs the same loop;
its first act is restoring the rolled-back checkpoint, so it enters
step ``k`` holding exactly what the survivors hold.

Device-victim semantics: a :class:`~zhpe_ompi_tpu.core.errors.
DeviceFault` naming THIS rank re-raises out of :meth:`run` — the rank
was classified dead and flooded; it must not impersonate a survivor
(on the real-process plane it never gets here: the wedge parks it
until the respawn SIGKILLs the declared-dead incarnation).
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable

from ..core import errors
from ..ft import recovery
from ..mca import output as mca_output
from ..runtime import spc

_stream = mca_output.open_stream("ftloop")


class FtTrainLoop:
    """See the module docstring for the contract."""

    def __init__(self, proc, *, step_fn: Callable, state: Any,
                 checkpointer, ckpt_every: int = 1, probe=None,
                 prober=None, wedge=None,
                 respawner: Callable | None = None,
                 remesh_fn: Callable | None = None,
                 shardings_fn: Callable | None = None,
                 rejoin_timeout: float = 30.0):
        if getattr(proc, "ft_state", None) is None:
            raise errors.UnsupportedError(
                "FtTrainLoop needs fault tolerance enabled (ft=True)")
        self.proc = proc
        self.step_fn = step_fn
        self.state = state
        self.ckpt = checkpointer
        self.ckpt_every = max(1, int(ckpt_every))
        self.probe = probe
        # the always-on half (parallel/mesh.DeviceProber): armed for
        # run()'s whole extent, quiet inside guarded regions and the
        # recovery leg (region() brackets both), probing the gaps —
        # data loading, checkpoint writes — where a wedge would
        # otherwise wait for the next collective to classify
        self.prober = prober
        self.wedge = wedge
        self.respawner = respawner
        self.remesh_fn = remesh_fn
        # device-plane state: ``shardings_fn(ep) -> shardings pytree``
        # lets the rollback restore MATERIALIZE directly onto the
        # endpoint's mesh (the survivor mesh mid-recovery; each device
        # reads only its extents) instead of staging full arrays on
        # the host.  None = host restore (remesh_fn re-partitions).
        self.shardings_fn = shardings_fn
        self.rejoin_timeout = float(rejoin_timeout)
        self.step_i = 0
        self.losses: list[float] = []
        self.recoveries = 0
        if probe is not None and probe.on_fault is None:
            probe.on_fault = self._on_device_fault
        # the ElasticSession traffic contract: step collectives ride a
        # generation-windowed dense endpoint (``live``), never the raw
        # one — a mid-collective fault then has a REVOCABLE window to
        # unblock stranded survivors through, and every recovery
        # re-shrinks into a provably fresh cid window.  A replacement's
        # constructor shrink pairs with the survivors' post-recovery
        # shrink (both ride the JOIN-adopted agreement counters).
        shrink = getattr(proc, "shrink", None)
        self.live = shrink() if callable(shrink) else proc

    # -- device-fault plumbing ---------------------------------------------

    def _on_device_fault(self, fault: errors.DeviceFault) -> None:
        """DeviceLivenessProbe on_fault hook (watchdog thread): flood
        the typed classification like a transport death, then release
        the injected wedge so an in-process drill's parked collective
        unwinds typed (a REAL wedge has nothing to release — the rank
        stays parked until the respawn kills it)."""
        flood = getattr(self.proc, "flood_device_fault", None)
        if flood is not None:
            flood(fault)
        if self.wedge is not None:
            self.wedge.release(fault)

    # -- the loop ----------------------------------------------------------

    def _guard(self):
        inner = self.probe.guard() if self.probe is not None \
            else contextlib.nullcontext()
        if self.prober is not None:
            # the guarded region silences the background prober (its
            # watchdog owns this window); the guard still arms inside
            return self.prober.region(inner)
        return inner

    def _checkpoint(self) -> None:
        # a collective checkpointer (io/ckptio.py, async_capable)
        # snapshots NOW and streams in the background, re-bound to the
        # current live window first so its gather cids revoke with the
        # mesh; steps keep committing while the previous checkpoint
        # drains (counted as ckpt_async_overlapped in run()).  The
        # serial Checkpointer stays blocking: a background pickle
        # racing a fault's rollback helps nobody
        bind = getattr(self.ckpt, "bind", None)
        if callable(bind):
            bind(self.live)
        self.ckpt.save(
            self.step_i, self.state,
            blocking=not getattr(self.ckpt, "async_capable", False))

    def restore(self, shardings=None) -> int:
        """Adopt the newest checkpoint (replacement ranks call this
        through run(); survivors through the rollback leg)."""
        self.state, step = recovery.rollback(self.ckpt,
                                             shardings=shardings)
        self.step_i = int(step)
        return self.step_i

    def run(self, steps: int) -> tuple[Any, list[float]]:
        """Run to ``steps`` total completed steps, surviving typed
        faults along the way.  Returns ``(state, losses)``."""
        if os.environ.get("ZMPI_REJOIN") == "1" and self.step_i == 0:
            # a replacement enters holding the rolled-back snapshot,
            # re-sharded onto the live window it joined
            self.restore(self.shardings_fn(self.live)
                         if self.shardings_fn is not None else None)
            if self.remesh_fn is not None:
                self.remesh_fn(self.live, self.state)
        if self.step_i == 0 and self.ckpt.latest_step() is None:
            self._checkpoint()  # step-0 snapshot: a fault before the
            # first interval still has a rollback point
        if self.prober is not None:
            self.prober.start()
        try:
            while self.step_i < steps:
                try:
                    with self._guard():
                        if self.wedge is not None:
                            self.wedge.tick()
                        self.state, loss = self.step_fn(
                            self.live, self.state, self.step_i)
                    self.step_i += 1
                    self.losses.append(float(loss))
                    if getattr(self.ckpt, "in_flight", False):
                        # the overlap gate: this step committed while
                        # the previous checkpoint's stream still drains
                        spc.record("ckpt_async_overlapped")
                    if self.step_i % self.ckpt_every == 0 \
                            or self.step_i == steps:
                        self._checkpoint()
                except errors.DeviceFault as e:
                    if self.proc.rank in e.failed_ranks:
                        raise  # THIS rank is the corpse: no survivor
                        # act
                    self._recover()
                except (errors.ProcFailed, errors.ProcFailedPending,
                        errors.Revoked):
                    # Revoked: a FELLOW survivor observed the fault
                    # first and revoked the live window to unblock this
                    # rank's parked collective — same recovery,
                    # different messenger
                    self._recover()
            # drain the last checkpoint's stream (and surface a
            # writer's pending failure) before declaring the run done
            wait = getattr(self.ckpt, "wait", None)
            if callable(wait):
                wait()
            # training done: one barrier before the caller finalizes,
            # so a fast rank's goodbye can never poison a peer still
            # receiving the last step's contributions (finalize skew —
            # the same race the DVM exit-frame fix closes one layer
            # down)
            barrier = getattr(self.live, "barrier", None)
            if callable(barrier):
                barrier()
        finally:
            if self.prober is not None:
                self.prober.stop()
        return self.state, self.losses

    def _recover(self) -> None:
        """The pipeline, end to end, collectively over the survivors.
        Runs inside a prober region: the background prober must not
        classify fresh faults against a plane mid-remesh."""
        with (self.prober.region() if self.prober is not None
              else contextlib.nullcontext()):
            self._recover_inner()

    def _recover_inner(self) -> None:
        if self.respawner is None:
            raise errors.UnsupportedError(
                "FtTrainLoop: a typed fault arrived with no respawner "
                "configured — pass respawner=recovery.daemon_respawn "
                "(DVM jobs) or a thread-plane respawn loop")
        self.recoveries += 1
        mca_output.verbose(
            1, _stream, "rank %d: typed fault; entering recovery %d",
            self.proc.rank, self.recoveries,
        )
        # unblock FELLOW survivors parked in the live window's
        # collectives (a wedged participant strands every rank whose
        # pending recv names a live-but-stalled peer): revoke the
        # window — they surface Revoked and enter this same recovery
        revoke = getattr(self.live, "revoke", None)
        if callable(revoke):
            try:
                from ..coll import host as coll_host

                revoke(coll_host.COLL_CID)
            except errors.MpiError:
                pass

        def rollback_fn(shrunk):
            # the REMESH leg: restore the rolled-back snapshot —
            # directly onto the SURVIVOR mesh when the app provides
            # shardings (each device reads only its extents) — then
            # let the app re-shard its partitioned state onto the
            # survivor endpoint (optimizer chunks, grad-sync
            # partitions)
            self.restore(self.shardings_fn(shrunk)
                         if self.shardings_fn is not None else None)
            if self.remesh_fn is not None:
                self.remesh_fn(shrunk, self.state)

        shrunk, victims = recovery.respawn_victims(
            self.proc, self.respawner, rollback_fn=rollback_fn,
            timeout=self.rejoin_timeout)
        for v in victims:
            if not recovery.await_rejoin(self.proc, v,
                                         self.rejoin_timeout):
                raise errors.InternalError(
                    f"recovery: rank {v} never rejoined within "
                    f"{self.rejoin_timeout}s")
        # full size again, in a FRESH window (the ElasticSession
        # resize sequence): every member raises the epoch floor once,
        # invalidates the locality topology, and re-shrinks — the
        # replacement's constructor shrink pairs with this one.  The
        # replacements restored the same snapshot, so every rank holds
        # identical state at the rolled-back step.
        state = self.proc.ft_state
        state.raise_epoch(state.crash_epoch() + 1)
        from ..coll import han as han_mod

        han_mod.invalidate(self.proc)
        self.live = self.proc.shrink()
        if self.remesh_fn is not None:
            self.remesh_fn(self.live, self.state)
        # roll the loss history back with the step counter (losses
        # past the checkpoint were un-learned by the rollback)
        del self.losses[self.step_i:]

