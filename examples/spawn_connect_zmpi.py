"""Dynamic process management acceptance example (reference:
test/simple/concurrent_spawn.c + intercomm_create.c shapes).

Demonstrates the wire plane's dpm end to end:

1. a 2-rank parent universe over real sockets,
2. MPI_Comm_spawn of 2 REAL child OS processes wired into their own
   universe,
3. intercommunicator collectives across the parent/child bridge
   (bcast + allreduce + barrier — the coll/inter composition),
4. children reporting back over the bridge before disconnect.

Run: python examples/spawn_connect_zmpi.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from zhpe_ompi_tpu import ops as zops  # noqa: E402
from zhpe_ompi_tpu.coll.inter import PROC_NULL, ROOT
from zhpe_ompi_tpu.comm import dpm_wire
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc


def child(proc, parent):
    # the child group computes together, then speaks to the parent
    team_sum = proc.allreduce(proc.rank + 1, zops.SUM)
    cfg = parent.bcast(None, root=0)  # from parent rank 0
    parent_sum = parent.allreduce(0, zops.SUM)  # parent group's total
    parent.send((proc.rank, team_sum, cfg, parent_sum), dest=0, tag=42)
    parent.barrier()


def parent_main(p):
    icomm, handle = dpm_wire.spawn(p, child, n_children=2)
    icomm.bcast({"lr": 0.1} if p.rank == 0 else None,
                root=ROOT if p.rank == 0 else PROC_NULL)
    icomm.allreduce(10 * (p.rank + 1), zops.SUM)  # children receive 30
    reports = None
    if p.rank == 0:
        reports = sorted(icomm.recv(source=r, tag=42) for r in range(2))
    icomm.barrier()
    if p.rank == 0:
        handle.join()
    return reports


def main():
    ready, addr = threading.Event(), [None]
    results = [None] * 2
    excs = []

    def run_rank(rank):
        try:
            if rank == 0:
                p = TcpProc(0, 2, ("127.0.0.1", 0),
                            on_coordinator_bound=lambda a: (
                                addr.__setitem__(0, a), ready.set()))
            else:
                ready.wait(10)
                p = TcpProc(rank, 2, addr[0])
            try:
                results[rank] = parent_main(p)
            finally:
                p.close()
        except BaseException as e:  # noqa: BLE001
            excs.append(e)
            ready.set()

    threads = [threading.Thread(target=run_rank, args=(r,))
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    if excs:
        raise excs[0]
    expect = [(0, 3, {"lr": 0.1}, 30), (1, 3, {"lr": 0.1}, 30)]
    assert results[0] == expect, results[0]
    print("spawn_connect: 2 parents + 2 spawned processes, intercomm "
          "bcast/allreduce/barrier across the bridge — PASSED")


if __name__ == "__main__":
    main()
