"""PGAS over mapped segments (sshmem/mmap analog, ``shmem/segment.py``).

Two tiers:
- the API surface over the mapped substrate with thread ranks (fast,
  same harness as the wire tests);
- REAL OS processes under the zmpirun launcher — direct loads/stores and
  native atomics against a mapping shared across address spaces, which
  is the property the reference's sshmem/mmap exists for.
"""

import io
import os
import textwrap

import numpy as np
import pytest

from test_tcp import run_tcp
from zhpe_ompi_tpu.shmem.api import shmem_mapped_pe
from zhpe_ompi_tpu.tools import mpirun

N = 4
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_mapped(n, fn, heap_bytes=1 << 16, timeout=60.0):
    def main(p):
        pe = shmem_mapped_pe(p, heap_bytes)
        try:
            return fn(pe)
        finally:
            pe.finalize()

    return run_tcp(n, main, timeout=timeout)


class TestMappedThreads:
    def test_circular_shift(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(4, np.float64)
            pe.local(sym)[...] = me
            pe.barrier_all()
            pe.put(sym, np.full(4, float(me)), (me + 1) % n)
            pe.barrier_all()
            got = pe.local(sym).copy()
            pe.barrier_all()
            pe.shfree(sym)
            return got.tolist()

        res = run_mapped(N, prog)
        for r in range(N):
            assert res[r] == [float((r - 1) % N)] * 4

    def test_amo_fetch_add_contention(self):
        """Every PE hammers PE 0's counter; the count must be exact
        (native __atomic path or flock fallback)."""
        ADDS = 200

        def prog(pe):
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            for _ in range(ADDS):
                pe.atomic_add(sym, 1, 0)
            pe.barrier_all()
            out = int(pe.local(sym)[0])
            pe.barrier_all()
            pe.shfree(sym)
            return out

        res = run_mapped(N, prog)
        assert res[0] == N * ADDS

    def test_amo_cas_swap_float(self):
        def prog(pe):
            sym = pe.shmalloc(2, np.float32)
            pe.local(sym)[...] = [1.5, 0.0]
            pe.barrier_all()
            if pe.my_pe() == 1:
                old = pe.atomic_compare_swap(sym, 1.5, 7.25, 0, index=0)
                assert old == np.float32(1.5), old
                old = pe.atomic_swap(sym, 3.0, 0, index=1)
                assert old == np.float32(0.0), old
            pe.barrier_all()
            out = pe.local(sym).copy() if pe.my_pe() == 0 else None
            pe.barrier_all()
            pe.shfree(sym)
            return None if out is None else out.tolist()

        res = run_mapped(N, prog)
        assert res[0] == [7.25, 3.0]

    def test_amo_index_bounds_checked(self):
        """Round-4 advisor fix: the native AMO path computes a raw address
        from the index — out-of-range (incl. negative) must raise, never
        touch memory outside the symmetric array."""
        from zhpe_ompi_tpu.core import errors

        def prog(pe):
            sym = pe.shmalloc(4, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            for bad in (-1, 4, 1000):
                try:
                    pe.atomic_add(sym, 1, 0, index=bad)
                    caught = False
                except errors.ArgError:
                    caught = True
                assert caught, f"index {bad} accepted"
            pe.barrier_all()
            out = int(pe.local(sym)[0])
            pe.shfree(sym)
            return out

        res = run_mapped(2, prog)
        assert res[0] == 0  # nothing landed

    def test_strided_iput_iget(self):
        def prog(pe):
            sym = pe.shmalloc(8, np.int32)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            if pe.my_pe() == 0:
                pe.iput(sym, np.arange(4, dtype=np.int32), 1, tst=2, sst=1)
            pe.barrier_all()
            got = pe.iget(sym, 1, 4, sst=2)
            pe.barrier_all()
            pe.shfree(sym)
            return got.tolist()

        res = run_mapped(2, prog)
        assert res[0] == [0, 1, 2, 3]

    def test_lock_mutual_exclusion(self):
        """Guarded non-atomic increments under shmem_set_lock must not
        lose updates."""
        ADDS = 50

        def prog(pe):
            lock = pe.shmalloc(1, np.int64)
            ctr = pe.shmalloc(1, np.int64)
            pe.local(ctr)[...] = 0
            pe.barrier_all()
            for _ in range(ADDS):
                pe.set_lock(lock)
                cur = int(pe.g(ctr, 0))
                pe.p(ctr, cur + 1, 0)
                pe.quiet()
                pe.clear_lock(lock)
            pe.barrier_all()
            out = int(pe.local(ctr)[0])
            pe.barrier_all()
            pe.shfree(ctr)
            pe.shfree(lock)
            return out

        res = run_mapped(N, prog)
        assert res[0] == N * ADDS

    def test_collectives_over_mapped(self):
        def prog(pe):
            n = pe.n_pes()
            src = pe.shmalloc(2, np.int32)
            dst = pe.shmalloc(2 * n, np.int32)
            pe.local(src)[...] = [pe.my_pe(), pe.my_pe() + 10]
            pe.barrier_all()
            pe.fcollect(dst, src)
            out = pe.local(dst).copy().tolist()
            pe.barrier_all()
            pe.shfree(dst)
            pe.shfree(src)
            return out

        res = run_mapped(N, prog)
        want = []
        for r in range(N):
            want += [r, r + 10]
        assert all(r == want for r in res)


def _script(tmp_path, body: str) -> str:
    p = tmp_path / "prog.py"
    p.write_text(
        "import sys\n"
        f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(body)
    )
    return str(p)


def _launch(n, argv):
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(n, argv, stdout=out, stderr=err, timeout=120.0)
    return rc, out.getvalue(), err.getvalue()


class TestMappedProcesses:
    """The cross-process proof: separate address spaces, one mapping."""

    def test_cross_process_put_amo(self, tmp_path):
        prog = _script(tmp_path, """
            import numpy as np
            import zhpe_ompi_tpu as zmpi
            from zhpe_ompi_tpu.shmem.api import shmem_mapped_pe

            proc = zmpi.host_init()
            pe = shmem_mapped_pe(proc, 1 << 16)
            me, n = pe.my_pe(), pe.n_pes()

            sym = pe.shmalloc(4, np.float64)
            pe.local(sym)[...] = me
            pe.barrier_all()
            pe.put(sym, np.full(4, float(me)), (me + 1) % n)
            pe.barrier_all()
            assert pe.local(sym).tolist() == [float((me - 1) % n)] * 4

            ctr = pe.shmalloc(1, np.int64)
            pe.local(ctr)[...] = 0
            pe.barrier_all()
            for _ in range(300):
                pe.atomic_add(ctr, 1, 0)
            pe.barrier_all()
            if me == 0:
                total = int(pe.local(ctr)[0])
                assert total == n * 300, total
                print("CROSS-PROC-OK")
            pe.finalize()
            zmpi.host_finalize()
        """)
        rc, out, err = _launch(4, [prog])
        assert rc == 0, err
        assert "CROSS-PROC-OK" in out

    def test_cross_process_wait_until(self, tmp_path):
        # PE 1 blocks in wait_until on its own memory; PE 0's put from
        # another PROCESS must wake it — store visibility across address
        # spaces
        prog = _script(tmp_path, """
            import numpy as np
            import zhpe_ompi_tpu as zmpi
            from zhpe_ompi_tpu.shmem.api import shmem_mapped_pe

            proc = zmpi.host_init()
            pe = shmem_mapped_pe(proc, 1 << 16)
            flag = pe.shmalloc(1, np.int64)
            pe.local(flag)[...] = 0
            pe.barrier_all()
            if pe.my_pe() == 0:
                pe.atomic_set(flag, 42, 1)
            elif pe.my_pe() == 1:
                pe.wait_until(flag, "eq", 42, timeout=30.0)
                print("WOKE")
            pe.barrier_all()
            pe.finalize()
            zmpi.host_finalize()
        """)
        rc, out, err = _launch(2, [prog])
        assert rc == 0, err
        assert "WOKE" in out


class TestMappedNbi:
    """put_nbi/get_nbi on the mapped substrate: stores are coherent once
    issued, so nbi completes immediately — the surface must still be
    uniform with the AM backend (quiet is the completion point)."""

    def test_nbi_roundtrip(self):
        def prog(pe):
            me, n = pe.my_pe(), pe.n_pes()
            sym = pe.shmalloc(4, np.float64)
            pe.local(sym)[...] = -1.0
            pe.barrier_all()
            pe.put_nbi(sym, np.full(4, float(me)), (me + 1) % n)
            pe.quiet()
            pe.barrier_all()
            buf = np.zeros(4, np.float64)
            pe.get_nbi(sym, (me + 1) % n, buf)
            pe.quiet()
            pe.barrier_all()
            pe.shfree(sym)
            return buf.tolist()

        res = run_mapped(3, prog)
        for r in range(3):
            assert res[r] == [float(r)] * 4
