"""Bookmark checkpoint coordination — crcp/bkmrk analog.

The reference's ``ompi/mca/crcp/bkmrk`` counts messages per peer pair and
exchanges the counts ("bookmarks") when a checkpoint is requested: if
rank i has sent more to rank j than j has received, the channel holds
in-flight data that must be drained before the snapshot is consistent.

Host-plane redesign: per-pair send/receive counters fed by the same
interposition hook the vprotocol logger uses, and a
:meth:`BookmarkCoordinator.quiescent` check that a checkpoint call can
gate on — making :mod:`zhpe_ompi_tpu.runtime.checkpoint`'s "checkpoint at
a quiescent point" contract verifiable per channel instead of assumed.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ..core import errors
from ..pt2pt.matching import ANY_SOURCE, ANY_TAG
from ..pt2pt.universe import LocalUniverse, RankContext


class BookmarkedContext:
    """RankContext proxy counting per-peer traffic."""

    def __init__(self, ctx: RankContext, coord: "BookmarkCoordinator"):
        self._ctx = ctx
        self._coord = coord
        self.rank = ctx.rank
        self.size = ctx.size

    def send(self, obj: Any, dest: int, tag: int = 0, cid: int = 0) -> None:
        self._ctx.send(obj, dest, tag, cid)
        self._coord._count_send(self.rank, dest)

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             cid: int = 0) -> Any:
        value, status = self._ctx.recv(source, tag, cid, return_status=True)
        self._coord._count_recv(status.source, self.rank)
        return value

    def sendrecv(self, obj: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG, cid: int = 0):
        rreq = self._ctx.irecv(source, recvtag, cid)
        sreq = self._ctx.isend(obj, dest, sendtag, cid)
        self._coord._count_send(self.rank, dest)
        value = rreq.wait()
        # deferred wire engine: the send completes (and the caller's
        # buffer is reusable) only at request completion, not at isend
        sreq.wait()
        self._coord._count_recv(rreq.status.source, self.rank)
        return value

    def barrier(self) -> None:
        self._ctx.barrier()


class BookmarkCoordinator:
    """Per-pair traffic bookmarks for a universe."""

    def __init__(self, uni: LocalUniverse):
        self._uni = uni
        n = uni.size
        self._sent = np.zeros((n, n), dtype=np.int64)
        self._recvd = np.zeros((n, n), dtype=np.int64)
        self._lock = threading.Lock()

    def wrap(self, ctx: RankContext) -> BookmarkedContext:
        return BookmarkedContext(ctx, self)

    def _count_send(self, src: int, dst: int) -> None:
        with self._lock:
            self._sent[src, dst] += 1

    def _count_recv(self, src: int, dst: int) -> None:
        with self._lock:
            self._recvd[src, dst] += 1

    def bookmarks(self) -> tuple[np.ndarray, np.ndarray]:
        """(sent, received) matrices — entry [i, j] counts i→j messages."""
        with self._lock:
            return self._sent.copy(), self._recvd.copy()

    def in_flight(self) -> np.ndarray:
        """Per-channel outstanding message counts (sent − received).
        FT-aware: channels touching a failed rank are exempt (zeroed) —
        a dead endpoint can never drain them, and the rollback owns
        whatever was in flight there."""
        sent, recvd = self.bookmarks()
        fl = sent - recvd
        state = getattr(self._uni, "ft_state", None)
        if state is not None:
            dead = sorted(state.failed())
            if dead:
                fl[dead, :] = 0
                fl[:, dead] = 0
        return fl

    def quiescent(self) -> bool:
        """True when every channel is drained — the bkmrk go/no-go
        decision for a consistent checkpoint."""
        return bool(np.all(self.in_flight() == 0))

    def require_quiescent(self) -> None:
        fl = self.in_flight()
        if np.any(fl != 0):
            pairs = [
                f"{i}->{j}:{int(fl[i, j])}"
                for i, j in zip(*np.nonzero(fl))
            ]
            raise errors.InternalError(
                "checkpoint requested on non-quiescent channels: "
                + ", ".join(pairs)
            )


class DistributedBookmarks:
    """Per-process bookmark counters with a collective quiescence check —
    the wire-plane form of the protocol (round-3 unweld): each rank keeps
    only its OWN row (`sent[j]`, `recvd[j]`), and :meth:`exchange` allgathers
    the rows at checkpoint time — exactly the reference's bkmrk handshake
    (``crcp_bkmrk_pml.c`` exchanges bookmarks between peers when a
    checkpoint is requested, because no shared matrix can exist across
    processes)."""

    def __init__(self, ctx):
        self._ctx = ctx
        n = ctx.size
        self.sent = np.zeros(n, dtype=np.int64)    # my sends, by dest
        self.recvd = np.zeros(n, dtype=np.int64)   # my receives, by source
        self._lock = threading.Lock()

    def wrap(self, ctx=None) -> "BookmarkedContext":
        """Proxy whose counters feed this rank's local rows."""
        return BookmarkedContext(ctx or self._ctx, self)

    # BookmarkedContext hooks (same interface as BookmarkCoordinator)
    def _count_send(self, src: int, dst: int) -> None:
        with self._lock:
            self.sent[dst] += 1

    def _count_recv(self, src: int, dst: int) -> None:
        with self._lock:
            self.recvd[src] += 1

    def exchange(self) -> tuple[np.ndarray, np.ndarray]:
        """Collective: gather every rank's rows into the full (sent,
        received) matrices — entry [i, j] counts i→j messages.

        FT-aware: with failed peers the full-membership allgather would
        wedge on the corpse, so on an ft endpoint the rows ALWAYS travel
        over a consensus-shrunk survivor endpoint (every survivor calls
        exchange collectively at checkpoint time, so the internal shrink
        is collective too).  Always: branching on LOCAL failure
        knowledge would let a survivor that has not yet seen an
        in-flight notice post the full-membership allgather while its
        peers run the consensus — divergent collective paths that
        deadlock.  The consensus round is the price of uniformity; with
        no failures it degenerates to a full-membership agreement and
        the "shrunk" endpoint IS the full job.  The dead ranks' rows
        stay zero; :meth:`in_flight` exempts their channels entirely —
        acked-failed peers' rows are the rollback's business, not
        quiescence's."""
        with self._lock:
            mine = (self.sent.tolist(), self.recvd.tolist())
        n = self._ctx.size
        state = getattr(self._ctx, "ft_state", None)
        if state is None:
            rows = self._ctx.allgather(mine)
            sent = np.array([r[0] for r in rows], dtype=np.int64)
            recvd = np.array([r[1] for r in rows], dtype=np.int64)
            return sent, recvd
        sh = self._ctx.shrink()
        rows = sh.allgather(mine)
        sent = np.zeros((n, n), dtype=np.int64)
        recvd = np.zeros((n, n), dtype=np.int64)
        for dense, row in enumerate(rows):
            parent = sh.group.ranks[dense]
            sent[parent] = row[0]
            recvd[parent] = row[1]
        return sent, recvd

    def in_flight(self) -> np.ndarray:
        """Collective: per-channel outstanding counts (sent[i,j] −
        recvd[j,i]).  Channels touching a failed rank are exempt
        (zeroed): no drain can ever clear them."""
        sent, recvd = self.exchange()
        fl = sent - recvd.T
        state = getattr(self._ctx, "ft_state", None)
        if state is not None:
            dead = sorted(state.failed())
            if dead:
                fl[dead, :] = 0
                fl[:, dead] = 0
        return fl

    def quiescent(self) -> bool:
        """Collective go/no-go: every channel drained on every rank."""
        return bool(np.all(self.in_flight() == 0))

    def require_quiescent(self) -> None:
        fl = self.in_flight()
        if np.any(fl != 0):
            pairs = [
                f"{i}->{j}:{int(fl[i, j])}"
                for i, j in zip(*np.nonzero(fl))
            ]
            raise errors.InternalError(
                "checkpoint requested on non-quiescent channels: "
                + ", ".join(pairs)
            )
