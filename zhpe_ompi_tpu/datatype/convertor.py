"""Convertor: pack/unpack engine for (possibly non-contiguous) datatypes.

Re-design of ``opal/datatype/opal_convertor.c:218-276`` for TPU.  The
reference's convertor is a resumable iovec-producing state machine walking a
datatype description; here the same roles are:

- **host path** — vectorized numpy byte-gather/scatter built from the
  optimized segment description (no per-primitive loop, no state machine:
  the whole index map is materialized once per (datatype, count) and cached,
  playing the role of the reference's prepared convertor).
- **device path** — for homogeneous datatypes, pack/unpack lower to
  ``jnp.take`` / scatter-``at[].set`` with a *static* index array, so XLA
  fuses them into surrounding computation and the data never leaves HBM
  (the inverse of the reference's CUDA path, which bounces device buffers
  through host memcpy — ``opal/datatype/opal_datatype_cuda.c``).
- **partial pack/unpack with a byte position** — MPI_Pack/Unpack semantics and
  the reference's convertor-position tests (``test/datatype/position.c``):
  byte-granular slicing of the index map.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native
from ..core import errors
from .derived import DerivedDatatype, merge_typemap_segments
from .predefined import Datatype

_seg_cache: dict[tuple, np.ndarray] = {}


def _segs_array(datatype: Datatype) -> np.ndarray:
    """(nsegs, 2) int64 array of one element's optimized description, for the
    native pack/unpack kernels."""
    segs = _one_element_segments(datatype)
    key = (tuple(segs),)
    arr = _seg_cache.get(key)
    if arr is None:
        arr = np.asarray(segs, dtype=np.int64).reshape(-1, 2)
        if len(_seg_cache) > 256:
            _seg_cache.clear()
        _seg_cache[key] = arr
    return arr


def _vp(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def _i64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _one_element_segments(datatype: Datatype) -> list[tuple[int, int]]:
    if isinstance(datatype, DerivedDatatype):
        return datatype.segments()
    return merge_typemap_segments(datatype.typemap())


def packed_size(datatype: Datatype, count: int) -> int:
    """MPI_Pack_size."""
    return datatype.size * count


def span_bytes(datatype: Datatype, count: int) -> int:
    """Bytes of source buffer spanned by `count` elements (true extent)."""
    if count == 0:
        return 0
    segs = _one_element_segments(datatype)
    last = max((d + n) for d, n in segs) if segs else 0
    return (count - 1) * datatype.extent + last


_index_cache: dict[tuple, np.ndarray] = {}


def byte_index_map(datatype: Datatype, count: int) -> np.ndarray:
    """Byte offsets (into the source buffer) of every payload byte of `count`
    elements, in pack order.  The cached analog of a prepared convertor.

    Cache is keyed by the type's structural identity (segments + extent), not
    object identity, so recycled ids can never alias a stale map.
    """
    segs = _one_element_segments(datatype)
    if segs and segs[0][0] < 0:
        raise errors.ArgError(
            f"datatype {datatype.name} has negative displacements "
            f"(lb={segs[0][0]}); pass a buffer starting at its true lower bound"
        )
    key = (tuple(segs), datatype.extent, count)
    cached = _index_cache.get(key)
    if cached is not None:
        return cached
    if not segs:
        idx = np.empty(0, dtype=np.int64)
    else:
        one = np.concatenate(
            [np.arange(d, d + n, dtype=np.int64) for d, n in segs]
        )
        starts = np.arange(count, dtype=np.int64) * datatype.extent
        idx = (starts[:, None] + one[None, :]).ravel()
    if len(_index_cache) > 256:
        _index_cache.clear()
    _index_cache[key] = idx
    return idx


def _as_byte_view(buffer) -> np.ndarray:
    if isinstance(buffer, np.ndarray):
        if not buffer.flags["C_CONTIGUOUS"]:
            raise errors.ArgError(
                "convertor buffers must be C-contiguous; the datatype itself "
                "describes the strided layout"
            )
        return buffer.reshape(-1).view(np.uint8)
    return np.frombuffer(buffer, dtype=np.uint8)


def _check_lb(datatype: Datatype) -> int:
    """Reject negative lower bounds (our buffers are 0-based) and
    non-positive extents (elements live at i * extent, so extent <= 0 would
    address before the buffer); return lb."""
    if datatype.extent < 0 or (datatype.extent == 0 and datatype.size > 0):
        raise errors.ArgError(
            f"datatype {datatype.name} has non-positive extent "
            f"({datatype.extent}); the pack engine requires extent > 0"
        )
    segs = _one_element_segments(datatype)
    lb = segs[0][0] if segs else 0
    if lb < 0:
        raise errors.ArgError(
            f"datatype {datatype.name} has negative displacements "
            f"(lb={lb}); pass a buffer starting at its true lower bound"
        )
    return lb


def pack(buffer, datatype: Datatype, count: int) -> np.ndarray:
    """Pack `count` elements of `datatype` from `buffer` into a contiguous
    uint8 array (cf. opal_convertor_pack)."""
    view = _as_byte_view(buffer)
    lb = _check_lb(datatype)
    need = span_bytes(datatype, count)
    if view.nbytes < need:
        raise errors.TruncateError(
            f"buffer of {view.nbytes}B too small for {count} x {datatype.name} "
            f"({need}B)"
        )
    if datatype.is_contiguous:
        return view[lb:need].copy()
    lib = native.load()
    if lib is not None:
        segs = _segs_array(datatype)
        out = np.empty(packed_size(datatype, count), dtype=np.uint8)
        src = np.ascontiguousarray(view)
        lib.zompi_pack(_vp(src), _vp(out), _i64p(segs), segs.shape[0],
                       datatype.extent, count)
        return out
    return view[byte_index_map(datatype, count)]


def unpack(packed, datatype: Datatype, count: int, out=None) -> np.ndarray:
    """Unpack a contiguous byte stream into the (strided) layout of `count`
    elements of `datatype` (cf. opal_convertor_unpack).  Returns the
    destination uint8 buffer."""
    src = _as_byte_view(packed)
    lb = _check_lb(datatype)
    need = packed_size(datatype, count)
    if src.nbytes < need:
        raise errors.TruncateError(
            f"packed stream of {src.nbytes}B too small ({need}B needed)"
        )
    span = span_bytes(datatype, count)
    if out is None:
        dest = np.zeros(span, dtype=np.uint8)
    else:
        dest = _as_byte_view(out)
        if dest.nbytes < span:
            raise errors.TruncateError("destination buffer too small")
    if datatype.is_contiguous:
        dest[lb : lb + need] = src[:need]
        return dest
    lib = native.load()
    if lib is not None and dest.flags["WRITEABLE"]:
        segs = _segs_array(datatype)
        srcc = np.ascontiguousarray(src[:need])
        lib.zompi_unpack(_vp(srcc), _vp(dest), _i64p(segs), segs.shape[0],
                         datatype.extent, count)
    else:
        dest[byte_index_map(datatype, count)] = src[:need]
    return dest


def pack_partial(
    buffer, datatype: Datatype, count: int, position: int, max_bytes: int
) -> tuple[np.ndarray, int]:
    """Resumable pack: emit up to `max_bytes` packed bytes starting at packed
    byte `position`; returns (chunk, new_position).  Byte-granular, so segment
    boundaries may be split exactly as the reference's convertor allows."""
    view = _as_byte_view(buffer)
    total = packed_size(datatype, count)
    if position < 0 or position > total:
        raise errors.ArgError(f"position {position} beyond packed size")
    need = span_bytes(datatype, count)
    if view.nbytes < need:
        raise errors.TruncateError(
            f"buffer of {view.nbytes}B too small for {count} x {datatype.name} "
            f"({need}B)"
        )
    end = min(position + max_bytes, total)
    lib = native.load()
    if lib is not None:
        _check_lb(datatype)
        segs = _segs_array(datatype)
        out = np.empty(end - position, dtype=np.uint8)
        newpos = lib.zompi_pack_partial(
            _vp(view), _vp(out), _i64p(segs), segs.shape[0],
            datatype.extent, count, position, end - position,
        )
        return out[: newpos - position], newpos
    idx = byte_index_map(datatype, count)
    return view[idx[position:end]], end


def unpack_partial(
    chunk, buffer, datatype: Datatype, count: int, position: int
) -> int:
    """Resumable unpack of a chunk that starts at packed byte `position` into
    `buffer`; returns the new position.  Chunks may arrive out of order
    (cf. test/datatype/unpack_ooo.c) — each lands at its own offsets."""
    src = _as_byte_view(chunk)
    dest = _as_byte_view(buffer)
    if position < 0:
        raise errors.ArgError(f"negative position {position}")
    end = position + src.nbytes
    if end > packed_size(datatype, count):
        raise errors.TruncateError("chunk overruns packed size")
    span = span_bytes(datatype, count)
    if dest.nbytes < span:
        raise errors.TruncateError(
            f"destination buffer of {dest.nbytes}B smaller than datatype "
            f"span ({span}B)"
        )
    lib = native.load()
    if lib is not None and dest.flags["WRITEABLE"]:
        _check_lb(datatype)
        segs = _segs_array(datatype)
        srcc = np.ascontiguousarray(src)
        return lib.zompi_unpack_partial(
            _vp(srcc), srcc.nbytes, _vp(dest), _i64p(segs), segs.shape[0],
            datatype.extent, count, position,
        )
    idx = byte_index_map(datatype, count)
    dest[idx[position:end]] = src
    return end


# ---------------------------------------------------------------------------
# Device (HBM-resident) path
# ---------------------------------------------------------------------------


def device_element_indices(datatype: Datatype, count: int) -> np.ndarray:
    """Static element-granularity gather indices for `count` elements of a
    homogeneous datatype (device path precondition)."""
    if isinstance(datatype, DerivedDatatype):
        base = datatype.element_indices()
        dt = datatype.homogeneous_dtype
        stride = datatype.extent // dt.itemsize
    else:
        tm = datatype.typemap()
        if len({np.dtype(t) for t, _ in tm}) != 1:
            raise errors.TypeError_(f"{datatype.name} is not homogeneous")
        dt = np.dtype(tm[0][0])
        base = np.asarray([d // dt.itemsize for _, d in tm])
        stride = datatype.extent // dt.itemsize
    starts = np.arange(count, dtype=np.int64) * stride
    return (starts[:, None] + base[None, :]).ravel()


def device_pack(x, datatype: Datatype, count: int):
    """Pack on device: HBM gather with static indices; jit/XLA-fusable.

    `x` is a jax array whose flattened element view underlies the datatype
    (its dtype must match the datatype's homogeneous dtype).
    """
    import jax.numpy as jnp

    flat = x.reshape(-1)
    item = np.dtype(flat.dtype).itemsize
    if datatype.is_contiguous and datatype.lb % item == 0:
        o = datatype.lb // item
        n = datatype.size * count // item
        return flat[o : o + n]
    idx = device_element_indices(datatype, count)
    return jnp.take(flat, idx, axis=0)


def device_unpack(packed, datatype: Datatype, count: int, out):
    """Unpack on device: HBM scatter with static indices into `out` (a flat
    jax array); returns the updated array (functional update)."""
    flat_out = out.reshape(-1)
    item = np.dtype(flat_out.dtype).itemsize
    if datatype.is_contiguous and datatype.lb % item == 0:
        o = datatype.lb // item
        n = packed.shape[0]
        return flat_out.at[o : o + n].set(packed[:n]).reshape(out.shape)
    idx = device_element_indices(datatype, count)
    return flat_out.at[idx].set(packed[: idx.shape[0]]).reshape(out.shape)
