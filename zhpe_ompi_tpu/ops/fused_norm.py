"""Fused layernorm Pallas kernel (fwd + bwd) — the round-4 MFU lever.

The round-3 cap analysis (bench.py docstring, benchmarks/mfu_sweep.py)
measured the steady-state plateau at 38-39% MFU and named the HBM-bound
segments between matmuls: the f32 layernorms are pure bandwidth — XLA
computes the row statistics and the normalize as separate passes with an
f32 upcast materialized in between, so each LN costs ~3x the minimal
traffic.  This kernel does the whole thing in one pass: a row block is
read into VMEM once (bf16), statistics and the normalized, gain-scaled
output are produced in-register in f32, and one bf16 block is written
back — the same "one read, one write" discipline as the flash kernels
(``ops/flash_attention.py``), applied to the norm.

Backward is a second one-pass kernel over the same row blocks using the
saved per-row (mean, rstd): dx from the standard layernorm backward
formula, dgamma accumulated across the sequential TPU grid in VMEM
scratch and written at the last step.

The reference has no analog (its hot loops are C over the wire,
SURVEY.md §2); this is TPU-only ground.  Reference numerics live in
``ln_reference`` — the models import the dispatcher, which falls back to
the reference off-TPU exactly like flash attention does.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_EPS = 1e-5


def ln_reference(x, g):
    """The single semantic baseline (transformer._ln's historical body):
    f32 statistics and normalize, cast back to the input dtype."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    m = xf.mean(-1, keepdims=True)
    v = xf.var(-1, keepdims=True)
    return ((xf - m) * lax.rsqrt(v + _EPS) * g).astype(dt)


# ---------------------------------------------------------------- forward


def _ln_fwd_kernel(x_ref, g_ref, y_ref, m_ref, r_ref):
    xf = x_ref[...].astype(jnp.float32)          # (block_rows, D)
    gf = g_ref[...].astype(jnp.float32)          # (1, D)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    c = xf - m
    v = jnp.mean(c * c, axis=-1, keepdims=True)
    r = lax.rsqrt(v + _EPS)
    y_ref[...] = (c * r * gf).astype(y_ref.dtype)
    m_ref[...] = m
    r_ref[...] = r


def _ln_fwd(x2, g, block_rows: int, interpret: bool):
    import jax.experimental.pallas as pl

    n, d = x2.shape
    grid = (n // block_rows,)
    y, m, r = pl.pallas_call(
        _ln_fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, g.reshape(1, d))
    return y, m, r


# ---------------------------------------------------------------- backward


def _ln_bwd_kernel(x_ref, g_ref, dy_ref, m_ref, r_ref, dx_ref, dg_ref,
                   dg_sc, *, n_blocks: int):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_sc[...] = jnp.zeros_like(dg_sc)

    xf = x_ref[...].astype(jnp.float32)
    gf = g_ref[...].astype(jnp.float32)
    dyf = dy_ref[...].astype(jnp.float32)
    m = m_ref[...]
    r = r_ref[...]
    xhat = (xf - m) * r
    dxhat = dyf * gf
    # dx = r * (dxhat - mean(dxhat) - xhat * mean(dxhat * xhat))
    mean_dxhat = jnp.mean(dxhat, axis=-1, keepdims=True)
    mean_dxx = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (r * (dxhat - mean_dxhat - xhat * mean_dxx)
                   ).astype(dx_ref.dtype)
    # dgamma: cross-row reduction, accumulated across the sequential grid
    dg_sc[...] += jnp.sum(dyf * xhat, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _emit():
        dg_ref[...] = dg_sc[...]


def _ln_bwd(x2, g, dy2, m, r, block_rows: int, interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = x2.shape
    n_blocks = n // block_rows
    dx, dg = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, n_blocks=n_blocks),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        interpret=interpret,
    )(x2, g.reshape(1, d), dy2, m, r)
    return dx, dg


# ------------------------------------------------------------- custom vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _ln_pallas(x2, g, block_rows, interpret):
    y, _, _ = _ln_fwd(x2, g, block_rows, interpret)
    return y


def _ln_vjp_fwd(x2, g, block_rows, interpret):
    y, m, r = _ln_fwd(x2, g, block_rows, interpret)
    return y, (x2, g, m, r)


def _ln_vjp_bwd(block_rows, interpret, res, dy):
    x2, g, m, r = res
    dx, dg = _ln_bwd(x2, g, dy, m, r, block_rows, interpret)
    return dx, dg.reshape(g.shape).astype(g.dtype)


_ln_pallas.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


# ------------------------------------------------------------- dispatcher


def _on_tpu() -> bool:
    dev0 = jax.devices()[0]
    kind = getattr(dev0, "device_kind", "").lower()
    return dev0.platform == "tpu" or any(
        t in kind for t in ("tpu", "v4", "v5", "v6", "trillium")
    )


_kernel_ok: bool | None = None
_warned = False


def _warn_fallback(reason: str) -> None:
    global _warned
    if not _warned:
        import warnings

        warnings.warn(
            f"Pallas fused-layernorm kernel unavailable ({reason}); "
            f"using the jnp reference", stacklevel=3,
        )
        _warned = True


def _kernel_available() -> bool:
    global _kernel_ok
    if _kernel_ok is None:
        import numpy as np

        try:
            x = jnp.ones((256, 256), jnp.bfloat16)
            out = _ln_pallas(x, jnp.ones((256,), jnp.float32), 128, False)
            _kernel_ok = bool(np.isfinite(np.asarray(out)).all())
            if not _kernel_ok:
                _warn_fallback("probe produced non-finite output")
        except Exception as e:  # noqa: BLE001
            _warn_fallback(type(e).__name__)
            _kernel_ok = False
    return _kernel_ok


def layer_norm(x, g, block_rows: int = 256, interpret: bool = False,
               force: bool = False):
    """Layernorm with gain over the last axis; Pallas one-pass kernel on
    TPU, reference jnp elsewhere.  ``force=True`` routes through the
    kernel anywhere (interpreted off-TPU, for tests); rows that do not
    tile the block fall back to the reference (the kernels want whole
    tiles, as flash does)."""
    d = x.shape[-1]
    n = 1
    for s in x.shape[:-1]:
        n *= s
    block = min(block_rows, n)
    if n % block or d % 128 or d < 128:
        return ln_reference(x, g)
    x2 = x.reshape(n, d)
    on_tpu = _on_tpu()
    if force:
        y = _ln_pallas(x2, g, block, interpret or not on_tpu)
        return y.reshape(x.shape)
    if not (on_tpu or interpret):
        return ln_reference(x, g)
    if on_tpu and not interpret and not _kernel_available():
        return ln_reference(x, g)
    try:
        y = _ln_pallas(x2, g, block, interpret)
        return y.reshape(x.shape)
    except Exception as e:  # noqa: BLE001 - lowering/executable failure
        _warn_fallback(f"{type(e).__name__} at shape {tuple(x.shape)}")
        return ln_reference(x, g)
