"""TCP transport tests (btl/tcp analog) — N procs over localhost sockets,
the wire-level counterpart of the thread-rank loopback tests."""

import threading

import numpy as np
import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.pt2pt.matching import ANY_SOURCE
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc

N = 4


def run_tcp(n, fn, timeout=60.0, sm=None):
    """Launch n TcpProcs in threads sharing a localhost coordinator.
    ``sm=False`` pins the pair to the wire — the tests asserting
    tcp_* counter/rendezvous behavior must not ride the shared-memory
    rings the selection ladder would otherwise pick."""
    coord_ready = threading.Event()
    coord_addr = [None]
    results = [None] * n
    excs = [None] * n

    def publish(addr):
        # ephemeral coordinator port -> other threads (on real deployments
        # this is the launcher's job, like prte forwarding the PMIx URI)
        coord_addr[0] = addr
        coord_ready.set()

    def main(rank):
        try:
            if rank == 0:
                proc = TcpProc(0, n, coordinator=("127.0.0.1", 0),
                               on_coordinator_bound=publish, sm=sm)
            else:
                coord_ready.wait(10)
                proc = TcpProc(rank, n, coordinator=coord_addr[0], sm=sm)
            try:
                results[rank] = fn(proc)
            finally:
                proc.close()
        except BaseException as e:  # noqa: BLE001
            excs[rank] = e
            coord_ready.set()

    threads = [threading.Thread(target=main, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "tcp rank hung"
    if any(e is not None for e in excs):
        # a stuck rank usually cascades: show every rank's state so the
        # ORIGIN of the stall is visible, not just the first timeout
        for r, e in enumerate(excs):
            if e is not None:
                print(f"[run_tcp] rank {r} raised: {type(e).__name__}: {e}",
                      flush=True)
        raise next(e for e in excs if e is not None)
    return results


class TestWire:
    def test_ring_token(self):
        def prog(p):
            token = p.rank
            p.send(token, dest=(p.rank + 1) % N, tag=1)
            return p.recv(source=(p.rank - 1) % N, tag=1)

        assert run_tcp(N, prog) == [(r - 1) % N for r in range(N)]

    def test_ndarray_payload(self):
        def prog(p):
            arr = np.arange(1000, dtype=np.float64) * p.rank
            p.send(arr, dest=(p.rank + 1) % N, tag=2)
            got = p.recv(source=(p.rank - 1) % N, tag=2)
            return float(got.sum())

        expect = [float(np.arange(1000).sum() * ((r - 1) % N))
                  for r in range(N)]
        assert run_tcp(N, prog) == expect

    def test_any_source_gather(self):
        def prog(p):
            if p.rank == 0:
                vals = sorted(p.recv(source=ANY_SOURCE, tag=3)
                              for _ in range(N - 1))
                return vals
            p.send(p.rank * 10, dest=0, tag=3)
            return None

        assert run_tcp(N, prog)[0] == [10, 20, 30]

    def test_tag_and_cid_isolation(self):
        def prog(p):
            if p.rank == 0:
                p.send("cid7", dest=1, tag=5, cid=7)
                p.send("cid9", dest=1, tag=5, cid=9)
                return True
            if p.rank == 1:
                # receive in the opposite cid order
                later = p.recv(source=0, tag=5, cid=9)
                first = p.recv(source=0, tag=5, cid=7)
                return (first, later)
            return None

        out = run_tcp(N, prog)
        assert out[1] == ("cid7", "cid9")

    def test_barrier_and_sendrecv(self):
        def prog(p):
            p.barrier()
            out = p.sendrecv(
                {"from": p.rank}, dest=(p.rank + 1) % N,
                source=(p.rank - 1) % N, sendtag=6, recvtag=6,
            )
            p.barrier()
            return out["from"]

        assert run_tcp(N, prog) == [(r - 1) % N for r in range(N)]

    def test_self_send_loopback(self):
        def prog(p):
            p.send(b"self", dest=p.rank, tag=8)
            return p.recv(source=p.rank, tag=8)

        assert run_tcp(2, prog) == [b"self", b"self"]

    def test_loopback_buffer_reuse_isolation(self):
        """The loopback shortcut skips serialization but must keep the
        defensive copy: mutate the source after send, the receiver sees
        the pre-mutation value (and the delivered array is writable)."""

        def prog(p):
            arr = np.arange(8, dtype=np.float64)
            p.send(arr, dest=p.rank, tag=9)
            arr[:] = -1.0  # sender reuses its buffer immediately
            got = p.recv(source=p.rank, tag=9)
            got += 0.0  # writable-delivery contract
            # container payloads get the same treatment (the (idx, block)
            # tuples host collectives ship)
            blk = np.ones(4)
            p.send((3, blk), dest=p.rank, tag=10)
            blk[:] = 7.0
            idx, got2 = p.recv(source=p.rank, tag=10)
            return (got.tolist(), idx, got2.tolist())

        out = run_tcp(2, prog)
        assert out[0] == (list(range(8)), 3, [1.0] * 4)

    def test_loopback_type_mapping_matches_dss(self):
        """Fast-path loopback must deliver the SAME types the DSS round
        trip would: bytearray lands as bytes, numpy scalars as 0-d
        arrays, tuples stay tuples."""

        def prog(p):
            p.send(bytearray(b"ba"), dest=p.rank, tag=11)
            p.send(np.float32(2.5), dest=p.rank, tag=12)
            a = p.recv(source=p.rank, tag=11)
            b = p.recv(source=p.rank, tag=12)
            return (type(a).__name__, a, type(b).__name__,
                    b.dtype.str, float(b))

        out = run_tcp(1, prog)
        assert out[0] == ("bytes", b"ba", "ndarray", "<f4", 2.5)

    def test_large_message(self):
        big = np.random.default_rng(0).normal(size=(512, 256))

        def prog(p):
            if p.rank == 0:
                p.send(big, dest=1, tag=9)
                return True
            if p.rank == 1:
                got = p.recv(source=0, tag=9)
                return bool(np.array_equal(got, big))
            return None

        assert run_tcp(2, prog) == [True, True]

    def test_recv_timeout_fatal_by_default(self):
        """Round-4 (VERDICT weak #4): transport timeouts dispatch through
        the errhandler — the communicator default is ERRORS_ARE_FATAL, so
        an unhandled timeout is a JobAbort carrying the typed cause."""
        from zhpe_ompi_tpu.core import errhandler as errh

        def prog(p):
            if p.rank == 0:
                with pytest.raises(errh.JobAbort) as ei:
                    p.recv(source=1, tag=99, timeout=0.3)
                assert isinstance(ei.value.cause, errors.InternalError)
            p.barrier()
            return True

        assert run_tcp(2, prog) == [True, True]

    def test_recv_timeout_errors_return(self):
        """ERRORS_RETURN: the same timeout comes back as the typed error
        (the reference's error-code return), no abort."""
        from zhpe_ompi_tpu.core import errhandler as errh

        def prog(p):
            p.set_errhandler(errh.ERRORS_RETURN)
            if p.rank == 0:
                with pytest.raises(errors.InternalError, match="timeout"):
                    p.recv(source=1, tag=99, timeout=0.3)
            p.barrier()
            return True

        assert run_tcp(2, prog) == [True, True]

    def test_peer_death_returns_error_not_stack_trace(self):
        """The VERDICT item-8 acceptance: a rank sets ERRORS_RETURN,
        its peer dies (closes without sending), and the waiting recv
        yields an error return the program can handle and continue
        from."""
        from zhpe_ompi_tpu.core import errhandler as errh

        def prog(p):
            if p.rank == 0:
                p.set_errhandler(errh.ERRORS_RETURN)
                got = None
                try:
                    got = p.recv(source=1, tag=7, timeout=1.0)
                except errors.MpiError as e:
                    # handled error return: the program continues
                    assert "timeout" in str(e)
                    return "survived"
                return got
            # rank 1 "dies": returns immediately, never sends
            return None

        res = run_tcp(2, prog)
        assert res[0] == "survived"

    def test_message_survives_abandoned_recv(self):
        """A message stolen by a timed-out receive must be re-injected so a
        retry still finds it."""

        def prog(p):
            from zhpe_ompi_tpu.core import errhandler as errh

            p.set_errhandler(errh.ERRORS_RETURN)
            if p.rank == 0:
                with pytest.raises(errors.InternalError, match="timeout"):
                    p.recv(source=1, tag=42, timeout=0.3)
                p.barrier()  # now rank 1 sends
                return p.recv(source=1, tag=42, timeout=5.0)
            p.barrier()
            p.send("late", dest=0, tag=42)
            return None

        assert run_tcp(2, prog)[0] == "late"

    def test_writable_ndarray_delivery(self):
        """Wire-delivered arrays must be writable, matching the thread
        universe's eager-copy semantics."""

        def prog(p):
            if p.rank == 0:
                p.send(np.arange(4, dtype=np.int64), dest=1, tag=11)
                return True
            got = p.recv(source=0, tag=11)
            got += 1  # raises on a read-only frombuffer view
            return got.tolist()

        assert run_tcp(2, prog)[1] == [1, 2, 3, 4]

    def test_ft_logging_over_sockets(self):
        """LoggedContext/BookmarkedContext-style wrapping works over the
        socket transport (return_status + irecv/isend compatibility)."""
        from zhpe_ompi_tpu.ft.vprotocol import LoggedContext, _RankLog
        import threading as _t

        def prog(p):
            log = _RankLog()
            wrapped = LoggedContext(p, log, _t.Lock())
            if p.rank == 0:
                wrapped.send(7, dest=1, tag=1)
                got = wrapped.recv(source=1, tag=2)
            else:
                got = wrapped.recv(source=0, tag=1)
                wrapped.send(got * 2, dest=0, tag=2)
            return (got, len(log.sends), len(log.recvs))

        out = run_tcp(2, prog)
        assert out[0] == (14, 1, 1) and out[1] == (7, 1, 1)


class TestZeroCopyWire:
    """The out-of-band frame path over real sockets: counters prove the
    fast path is taken, accounting covers actual on-wire bytes."""

    def test_zero_copy_counters_on_eager_array_send(self):
        from zhpe_ompi_tpu.runtime import spc

        arr = np.arange(4096, dtype=np.float64)  # 32 KB, eager

        def prog(p):
            if p.rank == 0:
                before = spc.read("tcp_zero_copy_sends")
                avoided = spc.read("tcp_copy_bytes_avoided")
                p.send(arr, dest=1, tag=60)
                p.recv(source=1, tag=61)
                return (spc.read("tcp_zero_copy_sends") - before,
                        spc.read("tcp_copy_bytes_avoided") - avoided)
            got = p.recv(source=0, tag=60)
            assert np.array_equal(got, arr) and got.flags.writeable
            p.send(b"ok", dest=0, tag=61)
            return None

        sends, avoided = run_tcp(2, prog, sm=False)[0]
        assert sends >= 1
        assert avoided >= arr.nbytes

    def test_zero_copy_counters_on_rendezvous_send(self):
        from zhpe_ompi_tpu.runtime import spc

        big = np.arange(1 << 18, dtype=np.float64)  # 2 MB > eager limit

        def prog(p):
            if p.rank == 0:
                before = spc.read("tcp_zero_copy_sends")
                p.send(big, dest=1, tag=62)
                p.recv(source=1, tag=63)
                return spc.read("tcp_zero_copy_sends") - before
            got = p.recv(source=0, tag=62, timeout=20.0)
            assert got.flags.writeable and float(got[-1]) == (1 << 18) - 1
            p.send(b"ok", dest=0, tag=63)
            return None

        assert run_tcp(2, prog, sm=False)[0] >= 1

    def test_bytes_sent_counts_wire_bytes(self):
        """tcp_bytes_sent must cover actual on-wire bytes: the 4-byte
        length headers and the payload frame — not just the DSS body
        (the seed under-counted headers and control frames)."""
        from zhpe_ompi_tpu.runtime import spc
        from zhpe_ompi_tpu.utils import dss

        arr = np.arange(1024, dtype=np.float64)

        def prog(p):
            if p.rank == 0:
                before = spc.read("tcp_bytes_sent")
                p.send(arr, dest=1, tag=64)
                sent = spc.read("tcp_bytes_sent") - before
                p.recv(source=1, tag=65)
                # at least the serialized frame + its length header
                return sent >= len(dss.pack(0, 64, 0, 0, arr)) + 4
            p.recv(source=0, tag=64)
            p.send(b"ok", dest=0, tag=65)
            return None

        assert run_tcp(2, prog, sm=False)[0] is True

    def test_rndv_wire_accounting_includes_control_frames(self):
        """A rendezvous transfer's RTS and CTS control frames (and the
        data connection's hello) are on-wire bytes too: the sender+
        receiver pair must record MORE than the bare data frame."""
        from zhpe_ompi_tpu.runtime import spc

        big = np.zeros(1 << 18, np.float64)  # 2 MB

        def prog(p):
            if p.rank == 0:
                p.barrier()
                before = spc.read("tcp_bytes_sent")
                p.send(big, dest=1, tag=66)
                p.recv(source=1, tag=67)  # transfer fully drained
                p.barrier()
                return spc.read("tcp_bytes_sent") - before
            p.barrier()
            p.recv(source=0, tag=66, timeout=20.0)
            p.send(b"done", dest=0, tag=67)
            p.barrier()
            return None

        # both ranks' counters land in the same process-global spc; the
        # delta spans RTS + CTS + hello + data + ack — strictly more
        # than the payload alone
        sent = run_tcp(2, prog, sm=False)[0]
        assert sent > big.nbytes

    def test_ft_and_zero_copy_coexist(self):
        """The fast path must ride UNDER the FT control plane, not
        around it: ft=True procs exchanging arrays still count
        zero-copy sends, and heartbeats/goodbyes keep flowing."""
        from zhpe_ompi_tpu.runtime import spc

        def prog(p):
            before = spc.read("tcp_zero_copy_sends")
            got = p.sendrecv(
                np.full(2048, float(p.rank + 1)), dest=1 - p.rank,
                source=1 - p.rank, sendtag=68, recvtag=68,
            )
            assert float(np.asarray(got)[0]) == float(2 - p.rank)
            return spc.read("tcp_zero_copy_sends") - before

        deltas = run_tcp_ft_pair(prog, sm=False)
        assert all(d >= 1 for d in deltas)


def run_tcp_ft_pair(fn, timeout=60.0, sm=None):
    """Two ft=True TcpProcs over localhost (detector armed) — the
    minimal fast-path + FT coexistence harness."""
    coord_ready = threading.Event()
    coord_addr = [None]
    results = [None, None]
    excs = [None, None]

    def publish(addr):
        coord_addr[0] = addr
        coord_ready.set()

    def main(rank):
        try:
            if rank == 0:
                proc = TcpProc(0, 2, coordinator=("127.0.0.1", 0),
                               on_coordinator_bound=publish, ft=True,
                               sm=sm)
            else:
                coord_ready.wait(10)
                proc = TcpProc(1, 2, coordinator=coord_addr[0], ft=True,
                               sm=sm)
            try:
                results[rank] = fn(proc)
            finally:
                proc.close()
        except BaseException as e:  # noqa: BLE001
            excs[rank] = e
            coord_ready.set()

    threads = [threading.Thread(target=main, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "ft tcp rank hung"
    if any(e is not None for e in excs):
        raise next(e for e in excs if e is not None)
    return results


class TestRendezvousPushPool:
    """Satellite: the per-rendezvous push thread spawn is capped by a
    small per-proc executor — a burst of large sends cannot spawn
    unbounded threads, and the pool drains at close()."""

    def test_burst_bounded_and_drains(self):
        from zhpe_ompi_tpu.mca import var as mca_var
        from zhpe_ompi_tpu.pt2pt import tcp as tcp_mod

        nmsg = 12
        cap = int(mca_var.get("tcp_rndv_push_workers", 4))
        big = np.zeros((1 << 17) + 16, np.float64)  # just over 1 MB

        def prog(p):
            if p.rank == 0:
                for i in range(nmsg):
                    p.send(big + float(i), dest=1, tag=70 + i)
                # every transfer is in flight now; the worker count must
                # stay at the pool cap even while pushes overlap
                peak = len(p._push_pool._threads)
                p.recv(source=1, tag=99, timeout=60.0)
                return peak
            total = 0.0
            for i in range(nmsg):
                got = p.recv(source=0, tag=70 + i, timeout=60.0)
                total += float(got[1])
            p.send(total, dest=0, tag=99)
            return total

        res = run_tcp(2, prog, sm=False)
        assert res[0] <= cap
        assert res[1] == float(sum(range(nmsg)))
        # pool drained at close(): the conftest session gate asserts the
        # same globally; check promptly here too
        assert tcp_mod.live_push_threads() == []
class TestRendezvous:
    """RTS/CTS above tcp_eager_limit: large payloads park at the SENDER
    until the receiver matches (round-3 fix of eager-only weakness)."""

    def test_large_message_rendezvous(self):
        from zhpe_ompi_tpu.mca import var as mca_var

        big = np.arange(1 << 18, dtype=np.float64)  # 2 MB > 1 MB limit

        def prog(p):
            if p.rank == 0:
                p.send(big, dest=1, tag=21)
                return True
            got = p.recv(source=0, tag=21, timeout=20.0)
            return bool(np.array_equal(got, big))

        assert run_tcp(2, prog, sm=False) == [True, True]

    def test_payload_parks_at_sender_until_matched(self):
        """The data frame must not cross the wire before the receiver
        posts a matching recv: the sender's pending table holds it."""

        def prog(p):
            big = np.zeros(1 << 18, np.float64)
            if p.rank == 0:
                p.send(big, dest=1, tag=22)  # returns after RTS only
                # data still pending (receiver hasn't matched)
                p.recv(source=1, tag=23)  # receiver: "I have NOT matched"
                pending_before = len(p._pending_rndv)
                p.send(b"now", dest=1, tag=24)
                got_back = p.recv(source=1, tag=25, timeout=20.0)
                # the push worker pops the parked entry in its finally,
                # AFTER its kernel-buffered data send returns — on an
                # oversubscribed box the receiver's round trip can beat
                # the preempted worker's pop by a few ms, so the
                # release is polled, not read instantaneously
                import time

                deadline = time.monotonic() + 5.0
                while p._pending_rndv and time.monotonic() < deadline:
                    time.sleep(0.005)
                pending_after = len(p._pending_rndv)
                return (pending_before, got_back, pending_after)
            import time

            time.sleep(0.3)  # let the RTS arrive unmatched
            p.send(b"unmatched", dest=0, tag=23)
            p.recv(source=0, tag=24)
            got = p.recv(source=0, tag=22, timeout=20.0)  # NOW match
            p.send(float(got.size), dest=0, tag=25)
            return None

        res = run_tcp(2, prog, sm=False)
        pending_before, got_back, pending_after = res[0]
        assert pending_before == 1  # parked at sender while unmatched
        assert got_back == float(1 << 18)
        assert pending_after == 0  # released after the CTS

    def test_interleaved_large_and_small(self):
        """Eager traffic keeps flowing while a rendezvous is pending, and
        two overlapping rendezvous sends resolve independently."""

        def prog(p):
            # strictly ABOVE the 1 MB limit (nbytes > limit is the
            # switch), so both transfers genuinely overlap as rendezvous
            a = np.full((1 << 17) + 8, 1.0)  # 1 MB + 64 B
            b = np.full(1 << 18, 2.0)        # 2 MB
            if p.rank == 0:
                p.send(b, dest=1, tag=31)
                p.send(a, dest=1, tag=30)
                p.send(b"small", dest=1, tag=32)
                return True
            small = p.recv(source=0, tag=32, timeout=20.0)
            gb = p.recv(source=0, tag=31, timeout=20.0)
            ga = p.recv(source=0, tag=30, timeout=20.0)
            return (small, float(ga[0]), ga.size, float(gb[0]), gb.size)

        res = run_tcp(2, prog, sm=False)
        assert res[1] == (b"small", 1.0, (1 << 17) + 8, 2.0, 1 << 18)

    def test_rendezvous_through_collectives(self):
        """A large-payload host-plane collective rides the rendezvous
        path transparently (coll rides the PML layering)."""

        def prog(p):
            big = np.full(1 << 18, float(p.rank + 1))
            out = p.allreduce(big, __import__(
                "zhpe_ompi_tpu.ops", fromlist=["SUM"]).SUM)
            return float(np.asarray(out)[0])

        assert run_tcp(4, prog, timeout=90.0, sm=False) == [10.0] * 4

    def test_bidirectional_large_exchange(self):
        """Two ranks streaming payloads far larger than the kernel
        socket buffers at each other must not deadlock: the rendezvous
        data push runs on its own thread over its own per-transfer
        connection, so neither the drain threads nor the control-plane
        framing lock can wedge behind a bulk sendall."""

        big = np.arange(1 << 23, dtype=np.float64)  # 64 MB each way

        def prog(p):
            other = 1 - p.rank
            got = p.sendrecv(big * (p.rank + 1), dest=other, source=other,
                             sendtag=44, recvtag=44)
            return float(np.asarray(got)[1])

        res = run_tcp(2, prog, timeout=90.0, sm=False)
        assert res == [2.0, 1.0]

    def test_container_payload_uses_rendezvous(self):
        """Tuple-wrapped large arrays must count their bytes for the
        eager/rendezvous switch (host collectives ship (idx, block)
        tuples)."""
        from zhpe_ompi_tpu.pt2pt.tcp import _payload_size

        arr = np.zeros(1 << 18, np.float64)  # 2 MB
        assert _payload_size(arr) == arr.nbytes
        assert _payload_size((3, arr)) >= arr.nbytes
        assert _payload_size([arr, arr]) >= 2 * arr.nbytes
        assert _payload_size({"k": arr}) >= arr.nbytes

        def prog(p):
            if p.rank == 0:
                p.send((7, arr), dest=1, tag=45)
                # the tuple must have parked (RTS sent, data pending)
                pending = len(p._pending_rndv)
                p.send(pending, dest=1, tag=46)
                return True
            import time

            time.sleep(0.3)  # leave the RTS unmatched for a moment
            pending = p.recv(source=0, tag=46, timeout=20.0)
            idx, got = p.recv(source=0, tag=45, timeout=20.0)
            return (pending, idx, got.size)

        res = run_tcp(2, prog, sm=False)
        # note: rank 0 sampled pending AFTER its own send returned but
        # possibly before rank 1 matched — it must have been >= 1 at RTS
        # time; by match time the transfer completes
        assert res[1][1] == 7 and res[1][2] == 1 << 18


class TestIsendDeferredContract:
    """Tentpole: true nonblocking isend — the buffer-reuse contract is
    DEFERRED to request completion.  wait() gates reuse: a buffer
    mutated AFTER wait() returns must deliver its PRE-mutation bytes,
    byte-exact, across every transport (eager wire / rendezvous wire /
    sm ring / loopback) and both planes (thread ranks and real
    sockets)."""

    @staticmethod
    def _sender(p, arr, want, tag, delay_recv=0.0):
        """rank 0: isend, wait, MUTATE, handshake; rank 1: (optionally
        delayed) recv + byte-exact check against the pre-mutation
        value."""
        import time

        if p.rank == 0:
            req = p.isend(arr, dest=1, tag=tag)
            req.wait(30.0)
            arr[:] = -1.0  # reuse AFTER completion
            p.send(b"mutated", dest=1, tag=tag + 1)
            return True
        if delay_recv:
            time.sleep(delay_recv)  # rendezvous: park while unmatched
        got = p.recv(source=0, tag=tag, timeout=30.0)
        p.recv(source=0, tag=tag + 1, timeout=30.0)
        return bool(np.array_equal(np.asarray(got), want))

    @pytest.mark.parametrize("nbytes,delay", [
        (8 << 10, 0.0),          # eager
        ((1 << 20) + 64, 0.2),   # rendezvous, parked while unmatched
    ])
    def test_socket_wire_matrix(self, nbytes, delay):
        from zhpe_ompi_tpu.runtime import spc

        arr = np.arange(nbytes // 8, dtype=np.float64)
        want = arr.copy()
        d0 = spc.read("tcp_isend_deferred")
        a0 = spc.read("rndv_park_bytes_avoided")
        c0 = spc.read("tcp_rndv_park_copy_bytes")

        res = run_tcp(2, lambda p: self._sender(p, arr, want, 50,
                                                delay_recv=delay),
                      sm=False)
        assert res == [True, True]
        assert spc.read("tcp_isend_deferred") > d0
        if arr.nbytes > (1 << 20):
            # the isend rendezvous parked the DESCRIPTOR, not a copy
            assert spc.read("rndv_park_bytes_avoided") - a0 >= arr.nbytes
            assert spc.read("tcp_rndv_park_copy_bytes") == c0

    @pytest.mark.parametrize("nbytes", [4 << 10, 1 << 20])
    def test_sm_ring_matrix(self, nbytes):
        """Same contract over the shared-memory rings (single-slot
        fast path and the fragment pipeline both)."""
        arr = np.arange(nbytes // 8, dtype=np.float64)
        want = arr.copy()
        res = run_tcp(2, lambda p: self._sender(p, arr, want, 52))
        assert res == [True, True]

    def test_loopback(self):
        def prog(p):
            arr = np.arange(512, dtype=np.float64)
            want = arr.copy()
            req = p.isend(arr, dest=0, tag=54)
            req.wait(10.0)
            arr[:] = -1.0
            got = p.recv(source=0, tag=54, timeout=10.0)
            return bool(np.array_equal(got, want))

        assert run_tcp(1, prog) == [True]

    @pytest.mark.parametrize("nbytes", [4 << 10, 256 << 10])
    def test_thread_plane_matrix(self, nbytes):
        """Thread ranks (LocalUniverse): eager copies at isend, the
        rendezvous handoff copies at CTS — wait() gates reuse on both."""
        from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse

        uni = LocalUniverse(2)
        arr = np.arange(nbytes // 8, dtype=np.float64)
        want = arr.copy()

        def prog(ctx):
            if ctx.rank == 0:
                req = ctx.isend(arr, dest=1, tag=56)
                req.wait(30.0)
                arr[:] = -1.0
                ctx.send(b"mutated", dest=1, tag=57)
                return True
            got = ctx.recv(source=0, tag=56)
            ctx.recv(source=0, tag=57)
            return bool(np.array_equal(np.asarray(got), want))

        assert uni.run(prog) == [True, True]

    def test_isend_send_fifo_interleave(self):
        """Per-source FIFO holds ACROSS the send paths: deferred isends
        and direct blocking sends to one peer arrive in program order
        (the blocking send fences on the channel)."""
        def prog(p):
            if p.rank == 0:
                reqs = []
                for i in range(12):
                    if i % 3 == 2:
                        p.send(i, dest=1, tag=60)
                    else:
                        reqs.append(p.isend(i, dest=1, tag=60))
                for r in reqs:
                    r.wait(20.0)
                return True
            return [p.recv(source=0, tag=60, timeout=20.0)
                    for _ in range(12)]

        res = run_tcp(2, prog, sm=False)
        assert res[1] == list(range(12))

    def test_wait_gates_reuse_on_parked_rendezvous(self):
        """A rendezvous isend stays INCOMPLETE while the receiver has
        not matched (the descriptor parks, nothing pushed), and wait()
        returns only once the pinned buffers crossed — the deferred
        contract, observable."""
        def prog(p):
            big = np.full((1 << 17) + 8, 7.0)  # just over the 1MB limit
            if p.rank == 0:
                req = p.isend(big, dest=1, tag=62)
                p.recv(source=1, tag=63, timeout=20.0)  # "not matched yet"
                parked = len(p._pending_rndv)
                done_early = req.done
                p.send(b"go", dest=1, tag=64)
                req.wait(30.0)
                return (parked, done_early)
            import time

            p.send(b"unmatched", dest=0, tag=63)
            p.recv(source=0, tag=64, timeout=20.0)
            time.sleep(0.05)
            got = p.recv(source=0, tag=62, timeout=30.0)
            return float(got[0])

        res = run_tcp(2, prog, sm=False)
        assert res[0] == (1, False)  # parked + incomplete while unmatched
        assert res[1] == 7.0

    def test_errored_request_on_revoked_cid(self):
        """Satellite: isend to a revoked cid returns an ERRORED request
        (typed at wait), never a synchronous raise — the waitall
        contract."""
        from zhpe_ompi_tpu.core import errhandler as errh

        def prog(p):
            p.set_errhandler(errh.ERRORS_RETURN)
            p.ft_state.revoke(77)
            req = p.isend(b"x", dest=1 - p.rank, tag=1, cid=77)
            assert req.done and req.error is not None
            with pytest.raises(errors.Revoked):
                req.wait()
            return True

        assert run_tcp_ft_pair(prog) == [True, True]
