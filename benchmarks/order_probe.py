"""Probe why vs_baseline reads ~1.09 when the HLO is identical.

Builds THREE timed states: the framework step (fw), the plain-JAX step
(pl), and a second, independently-jitted instance of the framework step
(fw2).  If fw2 tracks fw and not pl, the delta is in the program (HLO
diff missed something); if fw2 tracks pl, the delta follows build order
(allocation/compilation state), i.e. measurement procedure.

Run from repo root: python benchmarks/order_probe.py
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu import compat
    from zhpe_ompi_tpu.models import transformer as tfm

    devs = jax.devices()
    n = len(devs)
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.asarray(devs[: dp * tp]).reshape(dp, tp), ("dp", "tp"))
    dp_comm = zmpi.Communicator(mesh, "dp", name="probe_dp")
    tp_comm = zmpi.Communicator(mesh, "tp", name="probe_tp") if tp > 1 else None

    on_tpu = devs[0].platform not in ("cpu",)
    if on_tpu:
        cfg = tfm.Config(vocab=8192, d_model=1024, n_heads=16, d_ff=4096,
                         n_layers=4, seq=512, dtype=jnp.bfloat16)
        batch, iters = 8 * dp, 20
    else:
        cfg = tfm.Config(vocab=256, d_model=128, n_heads=8, d_ff=512,
                         n_layers=2, seq=128, dtype=jnp.float32)
        batch, iters = 2 * dp, 5

    r = np.random.default_rng(0)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
    targets = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))

    step_fw, specs = tfm.make_train_step(cfg, mesh, dp_comm, tp_comm)
    step_fw2, _ = tfm.make_train_step(cfg, mesh, dp_comm, tp_comm)

    from jax import lax

    class RawComm:
        def __init__(self, axis):
            self.axis = axis

        def allreduce(self, x, op):
            return lax.psum(x, self.axis)

    raw_tp = RawComm("tp") if tp > 1 else None

    def spmd_step(p, tok, tgt):
        def local_loss(pp):
            return tfm.loss_fn(pp, tok, tgt, cfg, raw_tp)

        loss, grads = jax.value_and_grad(local_loss)(p)
        synced = {}
        replicated = {"embed", "lnf", "ln1", "ln2"}
        for name, g in grads.items():
            g = lax.psum(g, "dp") / dp
            if name in replicated and raw_tp is not None:
                g = lax.psum(g, "tp") / tp
            synced[name] = g
        loss = lax.psum(loss, "dp") / dp
        if raw_tp is not None:
            loss = lax.psum(loss, "tp") / tp
        new_p = jax.tree.map(
            lambda a, g: (a - 1e-2 * g).astype(a.dtype), p, synced
        )
        return new_p, loss

    step_pl = jax.jit(compat.shard_map(
        spmd_step, mesh=mesh, in_specs=(specs, P("dp"), P("dp")),
        out_specs=(specs, P()), check_vma=False,
    ))

    def prep(step):
        sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                   for k, v in params.items()}
        dspec = NamedSharding(mesh, P("dp"))
        tok = jax.device_put(tokens, dspec)
        tgt = jax.device_put(targets, dspec)
        ps, loss = step(sharded, tok, tgt)
        for _ in range(3):
            ps, loss = step(ps, tok, tgt)
        float(loss)
        return {"step": step, "ps": ps, "tok": tok, "tgt": tgt,
                "best": float("inf"), "times": []}

    def window(st):
        step, tok, tgt, ps = st["step"], st["tok"], st["tgt"], st["ps"]
        t0 = time.perf_counter()
        for _ in range(iters):
            ps, loss = step(ps, tok, tgt)
        lval = float(loss)
        dt = (time.perf_counter() - t0) / iters
        st["times"].append(dt)
        st["best"] = min(st["best"], dt)
        st["ps"] = ps
        if not np.isfinite(lval):
            raise RuntimeError("non-finite")

    sts = {"fw": prep(step_fw), "pl": prep(step_pl), "fw2": prep(step_fw2)}
    order = ["fw", "pl", "fw2"]
    for i in range(6):
        rot = order[i % 3:] + order[:i % 3]
        for name in rot:
            window(sts[name])
    for name in order:
        st = sts[name]
        print(name, "best", round(st["best"] * 1e3, 3), "ms  all",
              [round(t * 1e3, 2) for t in st["times"]])


if __name__ == "__main__":
    main()
