"""Ring-pass under the zmpirun launcher — the reference's
``examples/ring_c.c:19-60`` run the way the reference runs it:
``mpirun -n 4 ring`` with real OS processes.

    python -m zhpe_ompi_tpu.tools.mpirun -n 4 examples/zmpirun_ring.py

Each rank joins the job with ``host_init()`` (the MPI_Init/PMIx-client
analog), passes a decrementing token around the ring, then allreduces a
check value across the job.
"""

import sys


def main():
    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu import ops as zops

    proc = zmpi.host_init()
    rank, size = proc.rank, proc.size
    nxt, prv = (rank + 1) % size, (rank - 1) % size

    laps = 3
    token = 0
    for _ in range(laps):
        if rank == 0:
            proc.send(token, nxt, tag=7)
            token = proc.recv(source=prv, tag=7)
        else:
            token = proc.recv(source=prv, tag=7)
            proc.send(token + 1, nxt, tag=7)
    if rank == 0:
        print(f"rank 0 token {token} after {laps} laps")
        if token != laps * (size - 1):
            sys.exit(1)

    total = proc.allreduce(rank, zops.SUM)
    expect = size * (size - 1) // 2
    if total != expect:
        print(f"rank {rank}: allreduce got {total} want {expect}")
        sys.exit(1)
    proc.barrier()
    if rank == 0:
        print("PASSED")
    zmpi.host_finalize()


if __name__ == "__main__":
    main()
