"""IO framework — MPI-IO re-designed for a single-controller array machine.

Reference shape (SURVEY.md §2.3): ``ompi/mca/io`` with the ``ompio``
component (``ompi/mca/io/ompio``), whose sub-frameworks split collective
strategy (``fcoll``), filesystem ops (``fs``), file byte transfer
(``fbtl``) and shared file pointers (``sharedfp``).

TPU-native re-design:

- A *file view* (``MPI_File_set_view``'s (disp, etype, filetype) triple) is
  interpreted by the same datatype engine that drives pack/unpack — the
  filetype's byte-index map tiles across the file exactly as
  ``ompi/mca/common/ompio/common_ompio_file_view.c`` decodes it.
- *Collective* IO on a single-controller machine: the controller holds
  every rank's buffer, so the two-phase aggregation of
  ``fcoll/two_phase`` collapses to "order the per-rank views, coalesce
  adjacent extents, issue large contiguous operations" — done in
  :meth:`File.write_all`/:meth:`File.read_all`.
- The idiomatic fast path is :mod:`zhpe_ompi_tpu.io.sharded`: a JAX
  ``NamedSharding`` IS a file view (each shard owns a disjoint file
  extent), so sharded-array save/load is MPI_File_write_all where the
  "ranks" are devices.
- ``fs`` components (posix today) are selected through the MCA framework
  machinery like every other component.
"""

from __future__ import annotations

from .file import (
    MODE_APPEND,
    MODE_CREATE,
    MODE_DELETE_ON_CLOSE,
    MODE_EXCL,
    MODE_RDONLY,
    MODE_RDWR,
    MODE_WRONLY,
    File,
    delete,
)
from .ckptio import CheckpointWriteError, CollectiveCheckpointer
from .sharded import load_sharded, save_sharded

__all__ = [
    "CollectiveCheckpointer",
    "CheckpointWriteError",
    "File",
    "delete",
    "MODE_RDONLY",
    "MODE_RDWR",
    "MODE_WRONLY",
    "MODE_CREATE",
    "MODE_EXCL",
    "MODE_APPEND",
    "MODE_DELETE_ON_CLOSE",
    "save_sharded",
    "load_sharded",
]
